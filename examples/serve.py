"""Offline batch serving on the flagship transformer.

The reference stops at training jobs; this is the inference-side workload
shape: read prompts (JSONL, one ``{"tokens": [...]}`` per line), serve
them in ragged mixed-length batches (right-padded per batch, per-row
positions — docs/SERVING.md), and write continuations back as JSONL.
Runs standalone or as a Mode-B task under the scheduler:

    tfrun -w 1 -s 0 -- python examples/serve.py --tiny --out /tmp/out.jsonl

Without ``--input``, a seeded synthetic workload (mixed prompt lengths)
stands in — this container has no egress, and untrained weights produce
token soup anyway; the point is the serving mechanics and throughput.
"""

import argparse
import json
import sys
import time

SPEC_N_DRAFT = 4    # draft tokens per speculative round (--speculative)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--input", type=str, default=None,
                   help="JSONL of {\"tokens\": [...]} prompts; synthetic "
                        "when absent")
    p.add_argument("--out", type=str, default=None,
                   help="output JSONL path (default stdout)")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--n-prompts", type=int, default=24, dest="n_prompts",
                   help="synthetic workload size (ignored with --input)")
    p.add_argument("--new-tokens", type=int, default=32, dest="new_tokens")
    p.add_argument("--stop-token", type=int, default=None, dest="stop_token")
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--int8", action="store_true")
    p.add_argument("--int8-kv", action="store_true", dest="int8_kv")
    p.add_argument("--int8-draft-kv", action="store_true",
                   dest="int8_draft_kv",
                   help="store the speculative draft's page pool int8 "
                        "(with --continuous --speculative)")
    p.add_argument("--paged", action="store_true",
                   help="serve from a paged KV cache: one shared page "
                        "pool, per-batch page allocation/recycling "
                        "(docs/SERVING.md)")
    p.add_argument("--continuous", action="store_true",
                   help="continuous batching: admit prompts into a "
                        "RUNNING paged decode as rows free up "
                        "(serving.ContinuousBatcher; --batch sets the "
                        "concurrent-row count)")
    p.add_argument("--speculative", action="store_true",
                   help="speculative continuous batching (with "
                        f"--continuous): a half-size draft proposes "
                        f"{SPEC_N_DRAFT} tokens per tick, the target "
                        "verifies them in one ragged chunk — greedy "
                        "outputs identical to target-only serving; "
                        "sampling is rejection-corrected to the "
                        "target's exact distribution")
    p.add_argument("--prefix-cache", type=int, default=0,
                   metavar="PAGES", dest="prefix_cache",
                   help="cross-request prefix cache budget in pool pages "
                        "per shard (with --continuous; 0 disables): "
                        "prompts sharing page-aligned leading chunks "
                        "prefill only their uncached tails")
    p.add_argument("--prefill-chunk", type=int, default=None,
                   dest="prefill_chunk",
                   help="chunked prefill (with --continuous): write "
                        "prompts in chunks of this many tokens, "
                        "interleaved with decode steps — bounds the "
                        "stall a long prompt imposes on decoding rows")
    p.add_argument("--multi-step", type=int, default=1, dest="multi_step",
                   help="decode K tokens per dispatch in continuous mode "
                        "(one host sync per [rows, K] block; stops act "
                        "at block granularity, token streams identical)")
    p.add_argument("--overlap", action="store_true",
                   help="double-buffered decode (with --continuous): "
                        "dispatch tick t+1 before syncing tick t's "
                        "tokens — hides per-token host round-trips; "
                        "token streams identical to non-overlap")
    p.add_argument("--pipeline-depth", type=int, default=0,
                   choices=(0, 1), dest="pipeline_depth",
                   help="pipelined device-resident decode (with "
                        "--continuous): 1 feeds each block from the "
                        "previous block's on-device tokens/positions/"
                        "steps and syncs one block behind — token "
                        "streams identical to 0 (the synchronous "
                        "default); mutually exclusive with --overlap")
    p.add_argument("--warmup", action="store_true",
                   help="compile every jitted serving entry point "
                        "before the stream starts (with --continuous; "
                        "ContinuousBatcher.warmup) — first-request "
                        "latency no longer pays the compiles")
    p.add_argument("--mesh", type=str, default=None,
                   help="multi-chip continuous serving (with "
                        "--continuous): comma-separated mesh axes, e.g. "
                        "dp=2,tp=2 — pool pages shard over dp, heads "
                        "over tp; --batch rows must divide over dp")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--tiny", action="store_true")
    args = p.parse_args()
    if args.mesh is not None and not args.continuous:
        p.error("--mesh is a continuous-batching feature; add --continuous")
    if args.int8_draft_kv and not args.speculative:
        p.error("--int8-draft-kv needs --continuous --speculative")
    if args.multi_step != 1 and not args.continuous:
        p.error("--multi-step is a continuous-batching feature; "
                "add --continuous")
    if args.overlap and not args.continuous:
        p.error("--overlap is a continuous-batching feature; "
                "add --continuous")
    if args.pipeline_depth and not args.continuous:
        p.error("--pipeline-depth is a continuous-batching feature; "
                "add --continuous")
    # --multi-step with --speculative and --pipeline-depth with
    # --overlap both construct now: the batcher composes the former (R
    # fused speculative rounds per dispatch) and records an enforced
    # bypass for the latter (overlap_bypass_reason) — see
    # serving.BYPASS_ALLOWLIST.
    if args.warmup and not args.continuous:
        p.error("--warmup is a continuous-batching feature; "
                "add --continuous")
    if args.paged and args.continuous:
        p.error("--paged and --continuous are distinct serving modes: "
                "--continuous already serves from a paged pool (pick one)")
    if args.prefill_chunk is not None and not args.continuous:
        p.error("--prefill-chunk is a continuous-batching feature; "
                "add --continuous")
    if args.speculative:
        if not args.continuous:
            p.error("--speculative here is a continuous-batching "
                    "feature; add --continuous (offline speculative "
                    "serving lives in examples/generate.py)")

    import jax
    import jax.numpy as jnp
    import numpy as np
    from tfmesos_tpu import runtime
    from tfmesos_tpu.models import transformer

    runtime.initialize()
    if args.tiny:
        cfg = transformer.TransformerConfig(
            vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_ff=128,
            max_seq_len=256, dtype=jnp.float32)
    else:
        cfg = transformer.TransformerConfig(
            vocab_size=8192, d_model=512, n_layers=8, n_heads=8, d_ff=1408,
            max_seq_len=4096, dtype=jnp.bfloat16)
    params = transformer.init_params(cfg, jax.random.PRNGKey(args.seed))
    if args.int8:
        params = jax.jit(
            lambda p_: transformer.quantize_params(cfg, p_))(params)

    if args.input:
        with open(args.input) as f:
            prompts = [json.loads(line)["tokens"] for line in f if line.strip()]
    else:
        rng = np.random.RandomState(args.seed)
        prompts = [rng.randint(0, cfg.vocab_size,
                               size=rng.randint(4, 33)).tolist()
                   for _ in range(args.n_prompts)]
    if not prompts:
        print("serve: empty workload", file=sys.stderr)
        return 1
    if any(len(t) == 0 for t in prompts):
        print("serve: empty prompt rows are not servable (there is no "
              "position to continue from)", file=sys.stderr)
        return 1
    limit = cfg.max_seq_len - args.new_tokens
    if any(len(t) > limit for t in prompts):
        print(f"serve: a prompt exceeds max_seq_len - new_tokens "
              f"({limit})", file=sys.stderr)
        return 1

    # One jitted servant per (padded_len) bucket: pad each batch to its
    # longest prompt rounded up to a multiple of 8, so a handful of
    # compiled shapes serves the whole stream.
    @jax.jit
    def run(params, batch, lens, cache=None):
        return transformer.generate(
            cfg, params, batch, args.new_tokens, prompt_lens=lens,
            rng=jax.random.PRNGKey(args.seed + 1),
            temperature=args.temperature, quantized_cache=args.int8_kv,
            stop_token=args.stop_token, cache=cache)

    if args.continuous:
        from tfmesos_tpu.serving import ContinuousBatcher, Request

        # Continuous mode has its own (tighter) length bound: prompts pad
        # to the prefill bucket, and speculative rounds overshoot by
        # n_draft on both the cache depth and the write high-water mark.
        nd = SPEC_N_DRAFT if args.speculative else 0
        bucket = args.prefill_chunk or 64
        # -1 in spec mode: the draft's backfill step writes one past the
        # proposals (ContinuousBatcher's depth check).
        ml = cfg.max_seq_len - (nd + 1 if nd else 0)
        # Overlap/pipelined endings surface late, so admission reserves
        # extra cache positions: a full overshoot round in speculative
        # mode, one position for a plain stop.  (Speculative decoding
        # bypasses --pipeline-depth explicitly, so its reservation only
        # follows --overlap.)
        ov = 0
        if args.overlap:
            ov = ((nd + 1) if args.speculative
                  else (1 if args.stop_token is not None else 0))
        elif args.pipeline_depth and not args.speculative:
            ov = 1 if args.stop_token is not None else 0
        climit = min((ml - nd - ov) // bucket * bucket,
                     ml - nd - ov - args.new_tokens + 1)
        if any(len(t) > climit for t in prompts):
            print(f"serve: a prompt exceeds the continuous-serving limit "
                  f"({climit} tokens at new-tokens={args.new_tokens}"
                  f"{', speculative' if args.speculative else ''})",
                  file=sys.stderr)
            return 1
        reqs = [Request(prompt=np.asarray(t, np.int32),
                        max_new_tokens=args.new_tokens,
                        stop_token=args.stop_token) for t in prompts]
        draft_cfg = draft_params = None
        if args.speculative:
            draft_cfg = transformer.TransformerConfig(
                vocab_size=cfg.vocab_size, d_model=cfg.d_model // 2,
                n_layers=max(1, cfg.n_layers // 2), n_heads=cfg.n_heads,
                d_ff=cfg.d_ff // 2, max_seq_len=cfg.max_seq_len,
                dtype=cfg.dtype)
            draft_params = transformer.init_params(
                draft_cfg, jax.random.PRNGKey(args.seed + 4))
        mesh = None
        if args.mesh is not None:
            from tfmesos_tpu.cli import parse_mesh
            from tfmesos_tpu.parallel.mesh import build_mesh
            mesh = build_mesh(parse_mesh(args.mesh))
        batcher = ContinuousBatcher(
            cfg, params, rows=args.batch, page_size=64, max_len=ml,
            temperature=args.temperature,
            rng=jax.random.PRNGKey(args.seed + 1),
            quantized_cache=args.int8_kv,
            prefill_chunk=args.prefill_chunk,
            draft_cfg=draft_cfg, draft_params=draft_params,
            n_draft=SPEC_N_DRAFT, mesh=mesh, overlap=args.overlap,
            draft_quantized_cache=args.int8_draft_kv,
            multi_step=args.multi_step,
            prefix_cache_pages=args.prefix_cache,
            pipeline_depth=args.pipeline_depth)
        if args.warmup:
            info = batcher.warmup()
            print(f"warmed {len(info['compiled'])} entry points in "
                  f"{info['seconds']:.1f}s", file=sys.stderr)
        sink = open(args.out, "w") if args.out else sys.stdout
        served = 0
        t0 = time.perf_counter()
        for c in batcher.run(reqs):
            sink.write(json.dumps({"rid": c.rid,
                                   "prompt_len": int(c.request.prompt.size),
                                   "tokens": c.tokens}) + "\n")
            served += 1
        dt = time.perf_counter() - t0
        if sink is not sys.stdout:
            sink.close()
        rate = batcher.acceptance_rate
        spec_note = ("" if rate is None
                     else f", draft acceptance {rate:.0%}")
        pst = batcher.prefix_cache_stats()
        pfx_note = ("" if pst is None else
                    f", prefix cache {pst['hits']}/{pst['hits'] + pst['misses']} hits "
                    f"({pst['hit_tokens']} tokens reused)")
        print(f"served {served} prompts continuously in {dt:.2f}s "
              f"(peak pages {batcher.peak_pages_used}/{batcher.n_pages}"
              f"{spec_note}{pfx_note})", file=sys.stderr)
        return 0

    alloc = pool = None
    if args.paged:
        # Pool sized for one batch at max shape — including the bucket
        # padding (prompts pad up to a multiple of 8, so the written
        # region can exceed limit+new_tokens by up to 7); pages recycle
        # between batches (a long-lived server would grow rows
        # incrementally).  --int8-kv composes: the pool stores int8 pages.
        page = 64
        max_width = -(-limit // 8) * 8
        per_row = -(-(max_width + args.new_tokens) // page)
        alloc = transformer.PageAllocator(args.batch * per_row, page)
        pool = transformer.init_paged_cache(cfg, args.batch * per_row,
                                            page_size=page,
                                            quantized=args.int8_kv)

    sink = open(args.out, "w") if args.out else sys.stdout
    served = 0
    t0 = time.perf_counter()
    for lo in range(0, len(prompts), args.batch):
        chunk = prompts[lo:lo + args.batch]
        lens = np.array([len(t) for t in chunk], np.int32)
        width = int(-(-max(lens) // 8) * 8)
        padded = np.zeros((len(chunk), width), np.int32)
        for i, t in enumerate(chunk):
            padded[i, :len(t)] = t
        if alloc is not None:
            # Pages must back the PADDED prompt region (prefill writes
            # the whole chunk) plus the continuation.
            for i in range(len(chunk)):
                alloc.ensure(i, width + args.new_tokens)
            cache = dict(pool, pages=alloc.table(range(len(chunk))))
            out = np.asarray(run(params, jnp.asarray(padded),
                                 jnp.asarray(lens), cache))
            for i in range(len(chunk)):
                alloc.release(i)
        else:
            out = np.asarray(run(params, jnp.asarray(padded),
                                 jnp.asarray(lens)))
        for i, t in enumerate(chunk):
            row = out[i, lens[i]:lens[i] + args.new_tokens].tolist()
            if args.stop_token is not None and args.stop_token in row:
                row = row[:row.index(args.stop_token) + 1]
            sink.write(json.dumps({"prompt_len": int(lens[i]),
                                   "tokens": row}) + "\n")
        served += len(chunk)
    dt = time.perf_counter() - t0
    if sink is not sys.stdout:
        sink.close()
    print(f"served {served} prompts ({served * args.new_tokens} tokens) "
          f"in {dt:.2f}s", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
