"""Project benchmark: mnist_replica steps/sec/chip (BASELINE.json metric),
plus MFU and memory/interconnect-bandwidth accounting (BASELINE.md §north
star).

Runs the reference's canonical workload — the mnist_replica trainer at its
published scale (batch 100, hidden 100, mnist_replica.py:70-73) — as a jit'd
sync-SGD step on this host's accelerator, the flagship transformer at
T=2048, and a compute-dense transformer config sized so the MXU (not the
VPU) bounds it.  Parse the LAST stdout JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
     "mfu_transformer": ..., "mfu_dense": ..., "allreduce_gbps": ...,
     "hbm_gbps": ...}

(once the headline metric is in hand, a flushed ``"partial": true`` line is
printed so an external timeout still leaves a parseable result; the final
full line supersedes it)

If the accelerator is unreachable (a wedged remote-attach relay hangs jax
backend init — this lost round 2's entire benchmark), the probe fails
over to CPU after the FIRST hang by default (round 4 burned 3x120s of
budget on retries that never cleared), emitting a real measured value
tagged ``"degraded"`` instead of a useless ``value: null``.  Knobs:
``TPUMESOS_PROBE_TIMEOUT_S`` (seconds per attempt, default 120) and
``TPUMESOS_PROBE_RETRIES`` (total attempts, default 1; raise it on hosts
whose relay claims are known to expire).  The round-2-era names
``TPUMESOS_BENCH_PROBE_TIMEOUT`` / ``TPUMESOS_BENCH_PROBE_ATTEMPTS``
are honored as fallbacks.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
baseline is our own round-1 value measured by the driver under this same
protocol (best-of-3, K fused steps per dispatch, timed region ends in a
device-to-host fetch), recorded in BASELINE_SELF below; >1.0 means faster
than round-1's framework, like for like.

MFU = analytic matmul FLOPs / elapsed / per-chip peak.  Peaks are the
published bf16 figures per device kind; an unknown kind falls back to the
v5e number and reports which peak it assumed.

Bandwidth: with >1 device, a psum sweep (1MB-256MB) reports achieved
all-reduce algorithmic bandwidth vs the ICI roofline; on a single chip there
is no ICI, so an HBM triad sweep reports memory bandwidth vs the HBM
roofline instead (the roofline that actually bounds single-chip kernels).
"""

import json
import time
from typing import Optional

import numpy as np

# Round-1 value for bench_mnist_replica measured by the round driver on one
# v5e chip under THIS protocol (BENCH_r01.json; see BASELINE.md for the
# protocol history).  Relay latency jitters ±40% between runs — read
# vs_baseline accordingly.
BASELINE_SELF = 10429.09


def _p99(vals):
    """Rank-index p99 shared by the fleet benches (priority, soak,
    trace overhead) — ONE estimator, so the benches cannot silently
    disagree about rounding."""
    vals = sorted(vals)
    return vals[min(len(vals) - 1, int(0.99 * len(vals)))]

# Published peak bf16 matmul throughput per chip and HBM bandwidth, by
# device kind string (jax.devices()[0].device_kind).
PEAK_BF16 = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
}
HBM_GBPS = {
    "TPU v4": 1228.0,
    "TPU v5 lite": 819.0,
    "TPU v5": 2765.0,
    "TPU v5p": 2765.0,
    "TPU v6 lite": 1640.0,
}
# Per-link ICI bandwidth (GB/s, one direction) — v5e: 4 links x ~100GB/s
# usable per chip; used only to contextualize the all-reduce number.
ICI_GBPS = {"TPU v5 lite": 400.0, "TPU v4": 300.0, "TPU v5p": 600.0}


def _device_kind():
    import jax

    return jax.devices()[0].device_kind


def _peak_flops():
    kind = _device_kind()
    return PEAK_BF16.get(kind, PEAK_BF16["TPU v5 lite"]), kind


def mlp_flops_per_step(cfg, batch: int) -> float:
    """Dense fwd+bwd ~= 6 FLOPs per weight per sample (2 fwd, 4 bwd)."""
    w = 784 * cfg.hidden + cfg.hidden * 10
    return 6.0 * w * batch


def transformer_flops_per_token(cfg, t: int) -> float:
    """Analytic matmul FLOPs per token, fwd+bwd (~3x forward).

    Per layer: qkv+out projections 4·d², swiglu 3·d·d_ff; unembed d·vocab;
    causal attention ≈ 2·T·d per layer per token (QKᵀ + PV at the average
    causal length T/2).  Elementwise work (norms, rope, softmax) is excluded
    — MFU measures MXU math against MXU peak.
    """
    per_layer_w = 4 * cfg.d_model ** 2 + 3 * cfg.d_model * cfg.d_ff
    w = cfg.n_layers * per_layer_w + cfg.d_model * cfg.vocab_size
    fwd = 2 * w + cfg.n_layers * 2 * t * cfg.d_model
    return 3.0 * fwd


def bench_mnist_replica(steps=2000, warmup=100):
    # Protocol (final, see BASELINE.md): K=20 optimizer steps fused per
    # dispatch via lax.scan; `steps` counts individual optimizer steps; the
    # timed chain ends in a real host fetch.  main() runs this best-of-3 to
    # shed remote-attach latency jitter.
    import jax
    import optax
    from tfmesos_tpu.models import mlp
    from tfmesos_tpu.parallel.mesh import build_mesh
    from tfmesos_tpu.parallel.sharding import make_global_batch
    from tfmesos_tpu.train import data as datalib
    from tfmesos_tpu.train.trainer import make_train_step

    n_chips = max(1, jax.device_count())
    mesh = build_mesh()  # every chip on a data-parallel axis
    cfg = mlp.MLPConfig(hidden=100)
    params = mlp.init_params(cfg, jax.random.PRNGKey(0))
    opt = optax.sgd(0.01)  # reference lr (mnist_replica.py:71)
    k = 20
    step = make_train_step(lambda p, b: mlp.loss_fn(cfg, p, b), opt, mesh=mesh,
                           steps_per_call=k)

    ds = datalib.SyntheticMNIST()
    # Reference batch 100, rounded so it shards evenly over the chips —
    # the step really runs on all of them, so dividing by n_chips is honest.
    local_bs = max(1, 100 // n_chips)
    gen = ds.batches(local_bs * n_chips)

    def stacked_batch():
        ms = [next(gen) for _ in range(k)]
        return make_global_batch(
            mesh, {key: np.stack([m[key] for m in ms]) for key in ms[0]},
            batch_dim=1)

    # jaxlib 0.4.x CPU: executing THIS program (donated params + fused
    # scan + multi-device all-reduce on virtual host devices) after a
    # persistent-compilation-cache DESERIALIZE corrupts the native heap
    # (malloc abort / SIGSEGV mid-run; a cold compile of the identical
    # program is fine, and no other program in the suite trips it).
    # Compile it fresh every time: the cache is disabled around the
    # compiling calls and the caller's setting restored after.
    cache_prev = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        params, opt_state = step.place(params, opt.init(params))
        batch = stacked_batch()
        for _ in range(max(1, warmup // k)):
            params, opt_state, metrics = step(params, opt_state, batch)
        float(metrics["loss"])  # drain the warmup chain with a real fetch
    finally:
        jax.config.update("jax_enable_compilation_cache", cache_prev)
    calls = max(1, steps // k)
    t0 = time.perf_counter()
    for _ in range(calls):
        params, opt_state, metrics = step(params, opt_state, batch)
    # Steps chain through donated params, so the device must run them in
    # order; the host fetch forces completion of the whole chain (on some
    # remote-attached runtimes block_until_ready acks early).
    final_loss = float(np.asarray(metrics["loss"]))
    dt = time.perf_counter() - t0
    steps_per_sec = calls * k / dt / n_chips
    peak, _ = _peak_flops()
    mfu = mlp_flops_per_step(cfg, local_bs * n_chips) * calls * k / dt / (
        n_chips * peak)
    return steps_per_sec, final_loss, mfu


def _bench_transformer_config(cfg_kwargs, b, t, k, iters=3):
    """Fused-scan transformer train-step timing; returns (tokens/s, mfu)."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax import lax
    from tfmesos_tpu.models import transformer

    cfg = transformer.TransformerConfig(max_seq_len=t, dtype=jnp.bfloat16,
                                        **cfg_kwargs)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    opt = optax.sgd(1e-4)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (k, b, t + 1), 0,
                                cfg.vocab_size, dtype=jnp.int32)

    @jax.jit
    def fused(params, opt_state, tokens):
        def body(carry, tok):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(
                lambda p: transformer.loss_fn(cfg, p, {"tokens": tok})[0]
            )(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        (params, opt_state), losses = lax.scan(body, (params, opt_state),
                                               tokens)
        return params, opt_state, losses[-1]

    p, s, loss = fused(params, opt_state, tokens)
    jax.block_until_ready(loss)
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        p, s, loss = fused(params, opt_state, tokens)
        float(np.asarray(loss))  # real device-to-host fetch ends the chain
        best = min(best, (time.perf_counter() - t0) / k)
    peak, _ = _peak_flops()
    tokens_per_sec = b * t / best
    mfu = transformer_flops_per_token(cfg, t) * b * t / best / peak
    return tokens_per_sec, mfu


def bench_transformer_tokens():
    """Flagship transformer (34M, d512) at T=2048, K=8 fused steps."""
    return _bench_transformer_config(
        dict(vocab_size=8192, d_model=512, n_layers=8, n_heads=8, d_ff=1408),
        b=8, t=2048, k=8)


def bench_transformer_dense():
    """Compute-dense config (d2048): the MXU-bound MFU probe.  The flagship's
    d512 layers leave the step partly VPU/elementwise-bound; this config
    shows the framework's ceiling when matmuls dominate."""
    return _bench_transformer_config(
        dict(vocab_size=8192, d_model=2048, n_layers=4, n_heads=16,
             d_ff=5632),
        b=4, t=2048, k=4)


def bench_decode(batch=8, prompt_len=128, new_tokens=256, quantized=False,
                 quantized_cache=False):
    """Steady-state decode throughput on the flagship config (KV cache,
    greedy): generated tokens per second across the batch.  The prompt is
    prefilled OUTSIDE the timed region — only the per-token scan is timed,
    so the metric stays comparable if the prompt/new-token ratio changes.

    ``quantized=True`` serves weight-only int8 params (per-row absmax,
    ``transformer.quantize_params``): t=1 decode is weight-bandwidth-bound,
    so halving the streamed bytes is the serving-side headline.
    ``quantized_cache=True`` additionally stores K/V as int8 — together
    they are the full int8 serving configuration."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from tfmesos_tpu.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=8192, d_model=512, n_layers=8, n_heads=8, d_ff=1408,
        max_seq_len=prompt_len + new_tokens, dtype=jnp.bfloat16)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    if quantized:
        params = jax.jit(
            lambda p: transformer.quantize_params(cfg, p))(params)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, prompt_len),
                                0, cfg.vocab_size, dtype=jnp.int32)
    cache0 = transformer.init_cache(cfg, batch, prompt_len + new_tokens,
                                    quantized=quantized_cache)
    prefill = jax.jit(lambda p, c, t: transformer.decode_step(cfg, p, c, t, 0))
    logits, cache = prefill(params, cache0, prompt)
    tok0 = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)

    @jax.jit
    def decode_loop(params, cache, tok):
        def body(carry, _):
            cache, tok, pos = carry
            logits, cache = transformer.decode_step(cfg, params, cache,
                                                    tok[:, None], pos)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            return (cache, nxt, pos + 1), None
        (cache, tok, _), _ = lax.scan(
            body, (cache, tok, jnp.asarray(prompt_len, jnp.int32)), None,
            length=new_tokens)
        return tok

    out = decode_loop(params, cache, tok0)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = decode_loop(params, cache, tok0)
        np.asarray(out)  # real fetch ends the chain
        best = min(best, time.perf_counter() - t0)
    return batch * new_tokens / best


def bench_decode_long_context(batch=4, max_len=16384, prompt_len=1024,
                              new_tokens=64):
    """Steady-state decode with a LONG cache buffer, early in generation —
    the flash-decode kernel's case: its scalar-prefetched block bound reads
    O(pos) cache slots while the XLA einsum pays for all ``max_len`` every
    step.  Returns (kernel_tok_s, einsum_tok_s); their ratio is the
    realized bandwidth saving (~max_len/pos bound at these shapes).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from tfmesos_tpu.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=8192, d_model=512, n_layers=8, n_heads=8, n_kv_heads=8,
        d_ff=1408, max_seq_len=max_len, dtype=jnp.bfloat16)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, prompt_len),
                                0, cfg.vocab_size, dtype=jnp.int32)
    cache0 = transformer.init_cache(cfg, batch, max_len)
    prefill = jax.jit(lambda p, c, t: transformer.decode_step(cfg, p, c, t, 0))
    logits, cache = prefill(params, cache0, prompt)
    tok0 = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)

    def loop_with(gate):
        from tfmesos_tpu.models import transformer as tr
        orig = tr._decode_kernel_kwargs
        tr._decode_kernel_kwargs = gate

        @jax.jit
        def decode_loop(params, cache, tok):
            def body(carry, _):
                cache, tok, pos = carry
                logits, cache = tr.decode_step(cfg, params, cache,
                                               tok[:, None], pos)
                nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                return (cache, nxt, pos + 1), None
            (cache, tok, _), _ = lax.scan(
                body, (cache, tok, jnp.asarray(prompt_len, jnp.int32)), None,
                length=new_tokens)
            return tok
        try:
            out = decode_loop(params, cache, tok0)
            jax.block_until_ready(out)
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                out = decode_loop(params, cache, tok0)
                np.asarray(out)
                best = min(best, time.perf_counter() - t0)
        finally:
            tr._decode_kernel_kwargs = orig
        return batch * new_tokens / best

    from tfmesos_tpu.models import transformer as tr
    kernel_gate = tr._decode_kernel_kwargs       # the real auto gate
    einsum_gate = lambda *a, **k: None           # force the XLA einsum
    return loop_with(kernel_gate), loop_with(einsum_gate)


def _timed_attention_fwdbwd(attn, b, t, h, d, reps):
    """Chained-scan fwd+bwd timing of one attention callable, ms per call.

    ``reps`` dependent grad steps inside one jit; the timed region ends in
    a host fetch (the remote-attach relay acks ``block_until_ready`` early,
    so independent calls mis-time).  Differentiates w.r.t. q AND k AND v:
    the flash custom_vjp always runs both backward kernels, so a q-only
    cotangent would let autodiff dead-code the reference's dk/dv paths and
    bias the comparison.  dq+dk+dv are q-shaped, so their sum chains the
    scan."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, t, h, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, t, h, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, t, h, d), jnp.bfloat16)

    g = jax.grad(lambda q_, k_, v_: jnp.sum(
        attn(q_, k_, v_).astype(jnp.float32) ** 2), argnums=(0, 1, 2))

    @jax.jit
    def chain(q0):
        def body(c, _):
            dq, dk, dv = g(c, k, v)
            return (dq + dk + dv).astype(jnp.bfloat16), None
        out, _ = lax.scan(body, q0, None, length=reps)
        return out

    out = chain(q)
    float(np.asarray(out[0, 0, 0, 0]))  # warm + drain
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = chain(q)
        float(np.asarray(out[0, 0, 0, 0]))
        best = min(best, (time.perf_counter() - t0) / reps)
    return best * 1000


def bench_attention(b=4, t=2048, h=8, d=128, reps=10):
    """Flash-kernel vs XLA-reference attention, fwd+bwd, at the BASELINE.md
    comparison shape (B4/T2048/H8/D128 bf16 causal).  Returns
    (flash_ms, xla_ms) per fwd+bwd call."""
    from tfmesos_tpu.ops.attention import flash_attention, mha_reference

    flash_ms = _timed_attention_fwdbwd(
        lambda q_, k_, v_: flash_attention(q_, k_, v_, causal=True),
        b, t, h, d, reps)
    xla_ms = _timed_attention_fwdbwd(
        lambda q_, k_, v_: mha_reference(q_, k_, v_, causal=True),
        b, t, h, d, reps)
    return flash_ms, xla_ms


def bench_attention_blocks(b=4, t=2048, h=8, d=128, reps=10):
    """Flash fwd+bwd per block_q choice — the recorded number BASELINE.md
    asks for before re-raising the default from 512.  Same chained-scan
    protocol as bench_attention; returns {"bq512": ms, "bq1024": ms}."""
    from tfmesos_tpu.ops.attention import flash_attention

    def timed(bq):
        return round(_timed_attention_fwdbwd(
            lambda q_, k_, v_: flash_attention(q_, k_, v_, causal=True,
                                               block_q=bq),
            b, t, h, d, reps), 3)

    return {"bq512": timed(512), "bq1024": timed(1024)}


def bench_attention_tsweep():
    """Flash vs XLA fwd+bwd across sequence lengths — the regime sweep
    behind the flash kernel's long-context claim (the win grows with T
    as XLA's O(T^2) score materialization saturates HBM; round-5
    measured ~2-3x at T=4k up to ~11x at T=8k on one v5e chip).  Each
    point is bench_attention at (b, t) — one protocol for the headline
    row and the sweep."""
    res = {}
    for t in (4096, 8192):
        b = 4 if t <= 4096 else 2
        reps = max(2, 10 * 2048 // t)
        f, x = bench_attention(b=b, t=t, reps=reps)
        res[f"t{t}"] = {"flash_ms": round(f, 2), "xla_ms": round(x, 2),
                        "speedup": round(x / f, 3)}
    return res


def pipeline_bubble_stats(pp=8, m=8):
    """STATIC 1F1B schedule analytics — no hardware needed, so even a
    CPU-degraded round records them.  Cost model: a forward tick costs
    1 unit of a full stage's forward, a backward tick 3 (recompute +
    backward — the schedule always remats from the stashed input), both
    scaled by 1/v at v virtual chunks; devices synchronize on the ring
    every tick, so wall-clock is the per-tick MAX over devices and the
    bubble is each device's idle share of that wall.
    ``interleave_speedup`` is the v=1 / v=2 wall ratio at equal work —
    the interleaved schedule's claim in one number.  Defaults measure
    the BUBBLE-BOUND regime (pp=8, m=8 — deep pipe, few microbatches)
    where interleaving exists to help (~1.2x there); at m >> pp the
    fill bubble amortizes away and the ratio approaches 1, and at
    pp=2 it can dip below (prefer v=1 there)."""
    import numpy as np
    from tfmesos_tpu.parallel.pipeline import _schedule_1f1b

    cost = np.array([0.0, 1.0, 3.0])    # idle / forward / backward
    out = {}
    walls = {}
    for v in (1, 2):
        kinds, _, _ = _schedule_1f1b(pp, m, v)
        per_tick = cost[kinds].max(axis=1) / v          # [T]
        wall = float(per_tick.sum())
        busy = float((cost[kinds] / v).sum())           # device work units
        out[f"pipeline_bubble_v{v}"] = round(1.0 - busy / (wall * pp), 4)
        walls[v] = wall
    out["pipeline_interleave_speedup"] = round(walls[1] / walls[2], 3)
    return out


def bench_ring_window(t=8192, window=1024, reps=10, interpret=False,
                      h=8, d=128):
    """Ring attention with a sliding window across every visible device:
    the Pallas offset-window inner (per-step kernels skip k-blocks
    outside the window — O(T·W) work ring-wide) vs the einsum inner.
    Needs >1 device (an sp axis); returns (flash_ms, einsum_ms) or None.
    ``interpret=True`` is the CI smoke path (Mosaic interpreter off-TPU)."""
    import jax
    import jax.numpy as jnp
    from tfmesos_tpu.parallel.mesh import build_mesh
    from tfmesos_tpu.parallel.ring_attention import ring_attention

    n = jax.device_count()
    if n < 2 or t % n:
        return None
    mesh = build_mesh({"sp": n})
    b = 1
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    dt = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    q = jax.random.normal(kq, (b, t, h, d), dt)
    k = jax.random.normal(kk, (b, t, h, d), dt)
    v = jax.random.normal(kv, (b, t, h, d), dt)

    def timed(impl):
        fn = jax.jit(lambda q_, k_, v_: ring_attention(
            q_, k_, v_, mesh, causal=True, window=window, impl=impl,
            interpret=interpret))
        jax.block_until_ready(fn(q, k, v))       # compile
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(reps):
                out = fn(q, k, v)
            jax.block_until_ready(out)
            best = min(best, (time.perf_counter() - t0) / reps)
        return best * 1000.0

    return timed("flash"), timed("xla")


def _serving_bench_setup(tiny: bool, max_len=None, plen=None, new=None):
    """(cfg, params, reqs-maker, max_len, new-tokens) for the serving
    benches — flagship config (with optional max_len/prompt/continuation
    overrides, so every serving bench shares ONE protocol), or a
    CI-affordable tiny one (which fixes its own sizes)."""
    import jax
    import jax.numpy as jnp
    from tfmesos_tpu.models import transformer
    from tfmesos_tpu.serving import Request

    if tiny:
        cfg = transformer.TransformerConfig(
            vocab_size=128, d_model=32, n_layers=2, n_heads=4, d_ff=64,
            max_seq_len=128, dtype=jnp.float32)
        max_len, plen, new = 64, 8, 4
    else:
        max_len = max_len or 1024
        plen, new = plen or 64, new or 64
        cfg = transformer.TransformerConfig(
            vocab_size=8192, d_model=512, n_layers=8, n_heads=8, d_ff=1408,
            max_seq_len=max_len, dtype=jnp.bfloat16)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)

    def reqs(n):
        return [Request(prompt=rng.integers(0, cfg.vocab_size, size=(plen,))
                        .astype(np.int32), max_new_tokens=new)
                for _ in range(n)]

    return cfg, params, reqs, max_len, new


def bench_serving_continuous(n_requests=32, rows=8, tiny=False):
    """Continuous-batching serving throughput: requests/s for a prompt
    stream admitted into a persistent paged decode
    (serving.ContinuousBatcher) — flagship config, or the tiny CI smoke
    config with ``tiny=True``."""
    from tfmesos_tpu.serving import ContinuousBatcher

    cfg, params, reqs, max_len, _ = _serving_bench_setup(tiny)
    batcher = ContinuousBatcher(cfg, params, rows=rows, max_len=max_len)
    list(batcher.run(reqs(2)))  # warm the compiles outside the timed region
    t0 = time.perf_counter()
    done = list(batcher.run(reqs(n_requests)))
    dt = time.perf_counter() - t0
    assert len(done) == n_requests
    mean_ttft_ms = 1000.0 * sum(c.ttft_s for c in done) / n_requests
    # Decode-phase inter-token p50 of the BASELINE loop — the number
    # the pipelined-decode bench (bench_serving_pipeline) is measured
    # against, recorded here so every round has the un-pipelined
    # reference even when the pipeline section is skipped.
    decode_itl_p50_ms = _itl_p50_ms(done)

    # Overlap mode: tick t+1 dispatched before tick t's tokens sync —
    # the win is one host round-trip per generated token, which through
    # this environment's relay is the dominant serving cost.
    ob = ContinuousBatcher(cfg, params, rows=rows, max_len=max_len,
                           overlap=True)
    list(ob.run(reqs(2)))
    t0 = time.perf_counter()
    odone = list(ob.run(reqs(n_requests)))
    overlap_rps = len(odone) / (time.perf_counter() - t0)

    # Multi-step blocks: K decode steps fused into ONE dispatch, one
    # host sync per [rows, K] token block.  Round-5 TPU profiling showed
    # per-tick dispatch+sync (~65 ms through the relay; real on any
    # host) dominating the batcher — this is the fix, measured.
    ms = ContinuousBatcher(cfg, params, rows=rows, max_len=max_len,
                           multi_step=16)
    list(ms.run(reqs(2)))
    t0 = time.perf_counter()
    mdone = list(ms.run(reqs(n_requests)))
    multistep_rps = len(mdone) / (time.perf_counter() - t0)

    mo = ContinuousBatcher(cfg, params, rows=rows, max_len=max_len,
                           multi_step=16, overlap=True)
    list(mo.run(reqs(2)))
    t0 = time.perf_counter()
    modone = list(mo.run(reqs(n_requests)))
    multistep_overlap_rps = len(modone) / (time.perf_counter() - t0)
    return (n_requests / dt, mean_ttft_ms, overlap_rps, multistep_rps,
            multistep_overlap_rps, decode_itl_p50_ms)


def _itl_p50_ms(completions) -> float:
    """p50 over per-completion mean decode inter-token gaps (the time
    AFTER the first token, normalized by the tokens that follow it)."""
    vals = sorted(1000.0 * (c.total_s - c.ttft_s)
                  / max(1, len(c.tokens) - 1) for c in completions)
    return vals[len(vals) // 2]


def bench_serving_pipeline(n_requests=16, rows=8, tiny=False):
    """Pipelined device-resident decode (``pipeline_depth=1``) vs the
    synchronous loop (``0``) on the SAME request objects in one
    process: the pipelined batcher feeds block N+1 from the device-side
    carry and syncs block N's tokens one block behind, so the decode
    inter-token p50 must be STRICTLY better — and since pipelining only
    moves the sync point, the outputs are asserted token-identical
    first (a faster wrong stream is not a result)."""
    from tfmesos_tpu.serving import ContinuousBatcher

    cfg, params, reqs, max_len, _ = _serving_bench_setup(tiny)
    warm_batch = reqs(2)
    batch = reqs(n_requests)    # ONE workload, served by both modes

    def run(depth):
        b = ContinuousBatcher(cfg, params, rows=rows, max_len=max_len,
                              pipeline_depth=depth)
        list(b.run(list(warm_batch)))   # compiles outside the timing
        t0 = time.perf_counter()
        done = sorted((c.rid, c) for c in b.run(list(batch)))
        dt = time.perf_counter() - t0
        assert len(done) == n_requests
        return ([c.tokens for _, c in done],
                _itl_p50_ms(c for _, c in done), n_requests / dt)

    base_tokens, base_itl, _ = run(0)
    pipe_tokens, pipe_itl, pipe_rps = run(1)
    assert pipe_tokens == base_tokens, \
        "pipelined completions diverged from the synchronous loop"
    assert pipe_itl < base_itl, \
        (f"pipelined decode inter-token p50 {pipe_itl:.3f}ms not "
         f"strictly better than synchronous {base_itl:.3f}ms")
    return pipe_itl, base_itl, pipe_rps


def bench_serving_fused_prefill(n_interactive=12, n_long=8, rows=4,
                                tiny=False, best_of=3):
    """Stall-free fused scheduling (docs/SERVING.md) vs the phase-split
    chunked tick on the SAME long-prompt-interference workload: short
    interactive requests decode while long prompts chunk in behind
    them.  Phase-split pays a separate chunk dispatch ahead of every
    decode block; the fused tick folds the budgeted chunk slots INTO
    the decode dispatch, so the interactive decode inter-token p99
    must be STRICTLY better fused — and since fusion only moves where
    the chunk rides, the streams are asserted token-identical first
    (a faster diverged stream is not a result).  The gap population is
    REAL per-token stream timestamps (``Request.on_tokens`` fires at
    every tick's flush), pooled across the interactive requests —
    interfered ticks are a large fraction of that pool, so the p99
    reads the stalled tick's duration, not one scheduler hiccup — and
    the reported number is the median of per-run p99s over
    ``best_of`` runs per mode."""
    from tfmesos_tpu.serving import ContinuousBatcher, Request

    cfg, params, _, max_len, _ = _serving_bench_setup(tiny)
    chunk = 8 if tiny else 64
    short_new = 24 if tiny else 48
    long_chunks = 7 if tiny else 5      # tiny max_len 64: 56 + 2 fits
    rng = np.random.default_rng(7)
    shorts = [rng.integers(0, cfg.vocab_size, size=(chunk,))
              .astype(np.int32) for _ in range(n_interactive)]
    longs = [rng.integers(0, cfg.vocab_size, size=(long_chunks * chunk,))
             .astype(np.int32) for _ in range(n_long)]

    def mk():
        # Shorts fill the rows first; each long admits as a row frees,
        # so there is (nearly) always a prompt chunking while the
        # resident shorts decode — the stall the fused tick removes.
        items = [Request(prompt=p.copy(), max_new_tokens=short_new)
                 for p in shorts[:rows]]
        rest = [Request(prompt=p.copy(), max_new_tokens=short_new)
                for p in shorts[rows:]]
        for i, p in enumerate(longs):
            items.append(Request(prompt=p.copy(), max_new_tokens=2))
            items.extend(rest[2 * i:2 * (i + 1)])
        items.extend(rest[2 * n_long:])
        return items

    n_total = n_interactive + n_long
    interactive_idx = {i for i, r in enumerate(mk())
                       if r.max_new_tokens == short_new}

    def run(fused):
        kw = dict(rows=rows, max_len=max_len, prefill_chunk=chunk,
                  fused_prefill=fused)
        tokens, p99s, dt = None, [], 1.0
        for _ in range(best_of):
            b = ContinuousBatcher(cfg, params, **kw)
            b.warmup()      # the whole grid AOT, incl. fused [w,S]
            items = mk()
            stamps = [[] for _ in items]
            for i in interactive_idx:
                def cb(toks, off, acc=stamps[i]):
                    acc.append(time.perf_counter())
                items[i].on_tokens = cb
            t0 = time.perf_counter()
            done = {c.rid: c for c in b.run(items)}
            dt = time.perf_counter() - t0
            assert len(done) == n_total
            if fused:
                assert b.fused_ticks > 0 and b.fused_chunk_tokens > 0, \
                    "fused batcher never fused a chunk into a tick"
            # rid assignment follows pull order — map completions back
            # to workload positions through the sorted rid sequence.
            tokens = [done[rid].tokens for rid in sorted(done)]
            gaps = sorted(1000.0 * (b2 - a)
                          for acc in stamps
                          for a, b2 in zip(acc, acc[1:]))
            assert len(gaps) >= 50, \
                "too few streamed gaps to read a p99 from"
            p99s.append(gaps[min(len(gaps) - 1,
                                 int(0.99 * len(gaps)))])
        return tokens, sorted(p99s)[len(p99s) // 2], n_total / dt

    split_tokens, split_p99, _ = run(False)
    fused_tokens, fused_p99, fused_rps = run(True)
    assert fused_tokens == split_tokens, \
        "fused completions diverged from the phase-split tick"
    assert fused_p99 < split_p99, \
        (f"interactive inter-token p99 under long-prompt interference "
         f"not strictly better fused: {fused_p99:.3f}ms vs phase-split "
         f"{split_p99:.3f}ms")
    return fused_p99, split_p99, fused_rps


def bench_decode_paged_call(tiny=False, reps=30):
    """Per-call paged-attention decode latency + launches-per-block —
    the device floor BASELINE.md round 5 localized (~0.54 ms/launch x
    8 launches per 16-step block) promoted to first-class bench keys
    so the floor is tracked across rounds instead of living in prose.

    Measures one jitted ``flash_decode_paged`` call at t=1 (the
    synchronous steady-state step) and at t=8 (the FUSED multi-row
    step a speculative verify dispatches: 8 decode rows retired
    through ONE launch per layer), plus the analytic launches a
    16-token block costs per mode
    (``ContinuousBatcher.paged_launches_per_block``) — the fused path
    asserted at <= 2, the acceptance bar."""
    import jax
    import jax.numpy as jnp
    from tfmesos_tpu.models import transformer
    from tfmesos_tpu.ops.attention import flash_decode_paged
    from tfmesos_tpu.serving import ContinuousBatcher

    if tiny:
        b, kv, g, d, ps, npg = 2, 2, 2, 16, 16, 4
    else:
        b, kv, g, d, ps, npg = 4, 4, 2, 64, 64, 16
    h = kv * g
    dt = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    key = jax.random.PRNGKey(0)
    kq, kk, kvv = jax.random.split(key, 3)
    pool_k = jax.random.normal(kk, (b * npg + 1, kv, ps, d), dt)
    pool_v = jax.random.normal(kvv, (b * npg + 1, kv, ps, d), dt)
    table = jnp.arange(b * npg, dtype=jnp.int32).reshape(b, npg)
    pos = jnp.full((b,), (npg - 1) * ps, jnp.int32)

    def timed(t):
        q = jax.random.normal(kq, (b, t, h, d), dt)
        self_kv = (jax.random.normal(kk, (b, t, kv, d), dt),
                   jax.random.normal(kvv, (b, t, kv, d), dt))
        fn = jax.jit(lambda q_, s_: flash_decode_paged(
            q_, pool_k, pool_v, table, pos, self_kv=s_))
        jax.block_until_ready(fn(q, self_kv))    # compile
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(reps):
                out = fn(q, self_kv)
            jax.block_until_ready(out)
            best = min(best, (time.perf_counter() - t0) / reps)
        return best * 1000.0

    call_ms, fused_ms = timed(1), timed(8)

    cfg, params, _, max_len, _ = _serving_bench_setup(True)
    sync = ContinuousBatcher(cfg, params, rows=2, max_len=max_len)
    dcfg = transformer.TransformerConfig(
        vocab_size=cfg.vocab_size, d_model=16, n_layers=1, n_heads=2,
        d_ff=32, max_seq_len=max_len + 8, dtype=jnp.float32)
    dparams = transformer.init_params(dcfg, jax.random.PRNGKey(1))
    spec = ContinuousBatcher(cfg, params, rows=2, max_len=max_len,
                             draft_cfg=dcfg, draft_params=dparams,
                             n_draft=7)
    sync_lpb = sync.paged_launches_per_block(16)
    fused_lpb = spec.paged_launches_per_block(16)
    assert fused_lpb <= 2, \
        (f"fused path costs {fused_lpb} paged launches per 16-step "
         f"block — the acceptance bar is <= 2")
    return call_ms, fused_ms, sync_lpb, fused_lpb


def bench_serving_warmup(rows=4, tiny=False):
    """First-request TTFT on a COLD batcher (the request pays the
    admission-prefill and first-decode compiles) vs a WARMED one
    (``ContinuousBatcher.warmup()`` built every executable at boot,
    off the serving path) — the fleet's ``warming`` replica state
    exists to buy exactly this, so warm must be STRICTLY below cold."""
    from tfmesos_tpu.serving import ContinuousBatcher

    cfg, params, reqs, max_len, _ = _serving_bench_setup(tiny)
    probe = reqs(1)
    cold = ContinuousBatcher(cfg, params, rows=rows, max_len=max_len)
    cold_done = list(cold.run(list(probe)))
    cold_ttft = 1000.0 * cold_done[0].ttft_s
    warm = ContinuousBatcher(cfg, params, rows=rows, max_len=max_len)
    warm_s = warm.warmup()["seconds"]
    warm_done = list(warm.run(list(probe)))
    warm_ttft = 1000.0 * warm_done[0].ttft_s
    assert warm_done[0].tokens == cold_done[0].tokens, \
        "warmup changed the served stream"
    assert warm_ttft < cold_ttft, \
        (f"warmed first-request TTFT {warm_ttft:.1f}ms not strictly "
         f"below cold {cold_ttft:.1f}ms")
    return warm_ttft, cold_ttft, warm_s


def bench_serving_prefix_cache(n_requests=16, rows=4, tiny=False):
    """Cross-request prefix caching on a shared-system-prompt workload
    (the dominant online pattern: one system/few-shot prompt, distinct
    user tails): mean TTFT with the prefix WARM in the cache vs COLD
    full prefill, plus warm throughput and the observed hit rate.  The
    correctness bar rides along: warm completions must EQUAL the
    cold-prefill completions."""
    from tfmesos_tpu.serving import ContinuousBatcher, Request

    if tiny:
        cfg, params, _, max_len, _ = _serving_bench_setup(True)
        page, sys_len, tail_len, new = 16, 40, 8, 4
    else:
        cfg, params, _, max_len, _ = _serving_bench_setup(False)
        page, sys_len, tail_len, new = 64, 448, 64, 32
    rng = np.random.default_rng(7)
    system = rng.integers(0, cfg.vocab_size, size=(sys_len,)).astype(np.int32)

    def reqs(n, seed=1):
        r2 = np.random.default_rng(seed)
        return [Request(prompt=np.concatenate(
                    [system, r2.integers(0, cfg.vocab_size,
                                         size=(tail_len,)).astype(np.int32)]),
                    max_new_tokens=new)
                for _ in range(n)]

    cold = ContinuousBatcher(cfg, params, rows=rows, max_len=max_len,
                             page_size=page, prefill_bucket=page)
    list(cold.run(reqs(2, seed=99)))    # warm the compiles only
    cold_done = sorted((c.rid, c) for c in cold.run(reqs(n_requests)))
    cold_ttft = 1000.0 * sum(c.ttft_s for _, c in cold_done) / n_requests

    warm = ContinuousBatcher(cfg, params, rows=rows, max_len=max_len,
                             page_size=page, prefill_bucket=page,
                             prefix_cache_pages=4 * (sys_len // page + 2))
    # Prime: compiles AND publishes the system prefix into the cache —
    # with a DISTINCT tail seed, so the measured stream hits only on
    # the shared system pages (a same-seed prime would make request 0
    # a byte-identical full-prompt hit and flatter the warm TTFT).
    list(warm.run(reqs(2, seed=99)))
    list(warm.run(reqs(1, seed=98)))
    t0 = time.perf_counter()
    warm_done = sorted((c.rid, c) for c in warm.run(reqs(n_requests)))
    dt = time.perf_counter() - t0
    warm_ttft = 1000.0 * sum(c.ttft_s for _, c in warm_done) / n_requests
    assert [c.tokens for _, c in warm_done] == \
        [c.tokens for _, c in cold_done], \
        "prefix-cached completions diverged from cold prefill"
    st = warm.prefix_cache_stats()
    hit_rate = st["hits"] / max(1, st["hits"] + st["misses"])
    return warm_ttft, cold_ttft, n_requests / dt, hit_rate


def bench_serving_spec_compose(n_requests=12, rows=4, tiny=False,
                               decode_new=24, migrate_requests=6,
                               strict=True):
    """Speculative decoding composed with the fast path (the bypass
    burn-down, ROADMAP item 6) — three arms:

    * ``serving_spec_warm_ttft_ms`` vs ``serving_spec_cold_ttft_ms`` —
      a SPECULATIVE batcher on the shared-system-prompt workload with
      the prefix cache warm (twin target+draft pages mapped read-only,
      only the tail prefilled through both writers) vs cold full
      prefill; warm asserted STRICTLY below cold, streams asserted
      EQUAL (a faster wrong stream is not a result).
    * ``serving_spec_decode_p50_intertoken_ms`` vs the non-speculative
      baseline on the same workload — measured with a PERFECT draft
      (draft == target): every round commits n_draft+1 tokens for one
      dispatch+sync.  RECORDED, not asserted: speculative decoding
      wins where decode is bandwidth/dispatch-bound (the accelerator
      regime); on this compute-bound CPU host a perfect draft costs
      ~2x target FLOPs per committed token, so wall-clock favors the
      baseline here by construction — the number tracks the overhead
      honestly (``serving_spec_acceptance_rate`` rides along, 1.0 for
      the perfect draft).
    * ``serving_spec_migration_lost_requests`` — a live 2-replica
      CPU fleet serving with drafts drain-MIGRATES one replica while
      spec requests are mid-decode: suspended rows move as KV exports
      CARRYING the draft-side payload and resume on the survivor;
      asserted zero lost with every stream equal to the local
      speculative reference.
    """
    import threading

    import jax
    import jax.numpy as jnp

    from tfmesos_tpu.fleet.client import FleetClient
    from tfmesos_tpu.fleet.launcher import FleetServer
    from tfmesos_tpu.fleet.replica import tiny_draft_model, tiny_model
    from tfmesos_tpu.models import transformer
    from tfmesos_tpu.serving import ContinuousBatcher, Request

    n_draft = 4
    if tiny:
        cfg, params, _, max_len, _ = _serving_bench_setup(True)
        page, sys_len, tail_len, new = 16, 40, 8, 4
        dcfg = transformer.TransformerConfig(
            vocab_size=cfg.vocab_size, d_model=16, n_layers=1,
            n_heads=2, d_ff=32, max_seq_len=max_len + n_draft + 1,
            dtype=jnp.float32)
    else:
        cfg, params, _, max_len, _ = _serving_bench_setup(False)
        page, sys_len, tail_len, new = 64, 448, 64, 16
        dcfg = transformer.TransformerConfig(
            vocab_size=cfg.vocab_size, d_model=128, n_layers=2,
            n_heads=4, d_ff=352, max_seq_len=max_len + n_draft + 1,
            dtype=jnp.bfloat16)
    dparams = transformer.init_params(dcfg, jax.random.PRNGKey(1))
    rng = np.random.default_rng(7)
    system = rng.integers(0, cfg.vocab_size,
                          size=(sys_len,)).astype(np.int32)

    def reqs(n, seed=1, mnt=new):
        r2 = np.random.default_rng(seed)
        return [Request(prompt=np.concatenate(
                    [system, r2.integers(0, cfg.vocab_size,
                                         size=(tail_len,))
                     .astype(np.int32)]), max_new_tokens=mnt)
                for _ in range(n)]

    spec_kw = dict(rows=rows, max_len=max_len, page_size=page,
                   prefill_bucket=page, draft_cfg=dcfg,
                   draft_params=dparams, n_draft=n_draft)
    # Arm 1: spec + prefix cache, warm vs cold TTFT (streams equal).
    cold = ContinuousBatcher(cfg, params, **spec_kw)
    list(cold.run(reqs(2, seed=99)))        # compiles only
    cold_done = sorted((c.rid, c) for c in cold.run(reqs(n_requests)))
    cold_ttft = 1000.0 * sum(c.ttft_s
                             for _, c in cold_done) / n_requests
    warm = ContinuousBatcher(cfg, params,
                             prefix_cache_pages=4 * (sys_len // page
                                                     + 2), **spec_kw)
    list(warm.run(reqs(2, seed=99)))        # compiles + publishes
    list(warm.run(reqs(1, seed=98)))        # distinct tail: shared hit
    warm_done = sorted((c.rid, c) for c in warm.run(reqs(n_requests)))
    warm_ttft = 1000.0 * sum(c.ttft_s
                             for _, c in warm_done) / n_requests
    assert [c.tokens for _, c in warm_done] == \
        [c.tokens for _, c in cold_done], \
        "spec prefix-cached completions diverged from spec cold prefill"
    # ``strict=False`` (the tiny CI smoke) keeps every CORRECTNESS
    # assert but lets the two timing wins pass un-asserted — toy
    # shapes invert timings; the flagship bench asserts both.
    assert not strict or warm_ttft < cold_ttft, \
        (f"spec+prefix warm TTFT {warm_ttft:.1f}ms not strictly below "
         f"spec cold TTFT {cold_ttft:.1f}ms")

    # Arm 2: spec inter-token p50 vs the non-spec baseline (perfect
    # draft = the ceiling; acceptance_rate rides along).  The perfect
    # draft IS the target config, whose max_seq_len must cover the
    # verify overshoot — both arms serve at the reduced max_len so
    # they measure the same workload.
    ml2 = max_len - n_draft - 1
    base = ContinuousBatcher(cfg, params, rows=rows, max_len=ml2,
                             page_size=page, prefill_bucket=page)
    list(base.run(reqs(2, seed=97)))
    base_done = list(base.run(reqs(n_requests, seed=3)))
    base_itl = _itl_p50_ms(base_done)
    perfect = ContinuousBatcher(cfg, params, rows=rows, max_len=ml2,
                                page_size=page, prefill_bucket=page,
                                draft_cfg=cfg, draft_params=params,
                                n_draft=n_draft)
    list(perfect.run(reqs(2, seed=97)))
    spec_done = list(perfect.run(reqs(n_requests, seed=3)))
    spec_itl = _itl_p50_ms(spec_done)
    accept = perfect.acceptance_rate or 0.0
    # No strict assert here (see the docstring): the CPU host is
    # compute-bound, where a perfect draft pays 2x FLOPs per token —
    # the recorded pair is the honest comparison, and the round-count
    # collapse is what the acceptance rate evidences.
    assert accept > 0.9, \
        f"perfect draft acceptance {accept:.3f} — the spec round is broken"

    # Arm 3: mid-stream drain migration of a SPEC fleet, zero lost.
    fleet = FleetServer(replicas=2, rows=2, tiny=True, max_len=64,
                        page_size=16, prefill_bucket=16, draft=True,
                        n_draft=3, workers=8, max_queue=64,
                        request_timeout=300.0, start_timeout=300.0)
    fleet.start()
    try:
        tcfg, tparams = tiny_model(seed=0)
        tdcfg, tdparams = tiny_draft_model(max_len=64, n_draft=3)
        ref_b = ContinuousBatcher(tcfg, tparams, rows=2, max_len=64,
                                  page_size=16, prefill_bucket=16,
                                  draft_cfg=tdcfg, draft_params=tdparams,
                                  n_draft=3)
        r2 = np.random.default_rng(11)
        prompts = [r2.integers(0, tcfg.vocab_size,
                               size=(9,)).astype(np.int32)
                   for _ in range(migrate_requests)]
        refs = {c.rid: c.tokens for c in ref_b.run(
            [Request(prompt=p.copy(), max_new_tokens=decode_new)
             for p in prompts])}
        client = FleetClient(fleet.addr, fleet.token, timeout=300.0)
        client.generate(prompts[0], 2)      # warm replica compiles
        results = [None] * migrate_requests
        errors = []

        def one(i):
            try:
                results[i] = client.generate(prompts[i], decode_new,
                                             timeout=300.0)
            except Exception as e:
                errors.append((i, e))

        threads = [threading.Thread(target=one, args=(i,), daemon=True)
                   for i in range(migrate_requests)]
        for t in threads:
            t.start()
        # Migrate whichever replica has work in flight, MID-decode.
        deadline = time.perf_counter() + 120.0
        victim = None
        while victim is None and time.perf_counter() < deadline:
            busy = [r for r in fleet.registry.alive()
                    if r.outstanding > 0]
            victim = busy[0].addr if busy else None
            time.sleep(0.02)
        assert victim is not None, "no replica ever reported work"
        fleet.request_migration(victim)
        for t in threads:
            t.join(timeout=300.0)
        assert not errors, f"spec request lost in migration: {errors[0]!r}"
        for i in range(migrate_requests):
            assert results[i]["tokens"] == refs[i], \
                f"migrated spec request {i} diverged from the reference"
        c = fleet.snapshot()["counters"]
        moved = (c.get("migration_resumes", 0)
                 + c.get("migration_reruns", 0))
        assert moved >= 1, f"migration never moved a request: {c}"
        resumes = int(c.get("migration_resumes", 0))
        client.close()
    finally:
        fleet.stop()
    return (warm_ttft, cold_ttft, spec_itl, base_itl, accept, resumes)


def bench_fleet_prefix_affinity(n_requests=24, replicas=2, rows=4,
                                n_prefixes=2, max_new_tokens=6,
                                workers=8):
    """Prefix-affinity routing through the full fleet front door:
    replicas run cross-request prefix caches and advertise them on
    heartbeats; the gateway steers each shared system prompt to the
    replica already holding it.  Reports the affinity hit rate (routing
    decisions that found a cached favorite) and warm requests/s."""
    import threading

    from tfmesos_tpu.fleet.client import FleetClient
    from tfmesos_tpu.fleet.launcher import FleetServer

    rng = np.random.default_rng(3)
    page = 16
    systems = [rng.integers(0, 97, size=(2 * page,)).astype(np.int32)
               for _ in range(n_prefixes)]
    fleet = FleetServer(replicas=replicas, rows=rows, tiny=True,
                        max_len=64, page_size=page, prefill_bucket=page,
                        prefix_cache_pages=32, workers=workers,
                        max_queue=max(64, 2 * n_requests),
                        start_timeout=300.0)
    fleet.start()
    try:
        client = FleetClient(fleet.addr, fleet.token, timeout=300.0)

        def run_batch(prompts):
            results = [None] * len(prompts)

            def one(i):
                results[i] = client.generate(prompts[i], max_new_tokens)

            threads = [threading.Thread(target=one, args=(i,))
                       for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return results

        def prompts(n, seed):
            r2 = np.random.default_rng(seed)
            return [np.concatenate(
                        [systems[i % n_prefixes],
                         r2.integers(0, 97, size=(4,)).astype(np.int32)])
                    for i in range(n)]

        # Prime: compiles + seeds every replica's cache, then give the
        # heartbeats a beat to advertise the summaries.
        run_batch(prompts(2 * replicas, seed=5))
        time.sleep(3.0 * fleet.heartbeat_interval + 0.2)
        t0 = time.perf_counter()
        results = run_batch(prompts(n_requests, seed=6))
        dt = time.perf_counter() - t0
        assert all(r is not None for r in results)
        snap = fleet.snapshot()["counters"]
        hits = snap.get("affinity_hits", 0)
        misses = snap.get("affinity_misses", 0)
        hit_rate = hits / max(1, hits + misses)
        client.close()
        return hit_rate, n_requests / dt
    finally:
        fleet.stop()


def bench_fleet_sessions(replicas=2, rows=4, turns=4, n_shared=8,
                         workers=8, max_new_tokens=8):
    """The fleet-wide KV economy (docs/SERVING.md "KV tiering &
    sessions"), both halves asserted in-bench:

    * SESSIONS — a multi-turn conversation on a KV-tiered fleet: each
      turn's full-history prompt is served twice, once cold (no
      session label — the whole history prefills) and once resumed
      (``session=`` — the parked turn's KV imports and only the new
      tail prefills, routed to the parker by session affinity).
      Resumed TTFT must be STRICTLY below cold, and the streams
      TOKEN-IDENTICAL (the uninterrupted-reference equivalence bar).
    * SHARED PREFIXES as a CLUSTER resource — a common system prompt
      on a prefix-cached fleet must be prefilled ONCE PER FLEET
      (router-directed seeding: affinity steers every later request to
      the replica already holding the pages), asserted by summing
      per-replica prefix-cache misses off the heartbeat summaries.

    Reports (resumed_ttft_ms, cold_ttft_ms, kv_tier_hit_rate,
    shared_prefix_prefills, shared_affinity_hit_rate)."""
    from tfmesos_tpu.fleet.client import FleetClient
    from tfmesos_tpu.fleet.launcher import FleetServer

    from tfmesos_tpu.fleet.kvtier import KVTierStore
    from tfmesos_tpu.serving import ContinuousBatcher, Request

    page = 16
    rng = np.random.default_rng(11)

    # -- Part A: session resume vs cold full-history prefill, on the
    # FLAGSHIP shape (the win IS skipped prefill compute — the tiny
    # model's prefill is too cheap to measure; fleet costs are covered
    # by part A2 below) in FLOAT32: the equivalence bar is exact token
    # equality, and bfloat16 argmax ties can flip between the fused
    # cold prefill and the resume path's tail chunk writer (the same
    # documented caveat chunked prefill carries).  One batcher serves
    # both arms: unlabeled requests prefill the whole history,
    # session-labeled ones resume from the tier; a priming
    # conversation of the same turn lengths warms every compile first,
    # so neither arm's TTFT carries a trace.
    import jax
    import jax.numpy as jnp
    from tfmesos_tpu.models import transformer as _tfm

    max_len = 1024
    cfg = _tfm.TransformerConfig(
        vocab_size=8192, d_model=512, n_layers=8, n_heads=8, d_ff=1408,
        max_seq_len=max_len, dtype=jnp.float32)
    params = _tfm.init_params(cfg, jax.random.PRNGKey(0))
    fpage, sys_len, user_len, new = 64, 448, 64, 32
    tier = KVTierStore(ram_bytes=256 << 20, token="bench")
    b = ContinuousBatcher(cfg, params, rows=2, max_len=max_len,
                          page_size=fpage, prefill_bucket=fpage,
                          kv_tier=tier)

    def conversation(sid, seed, measure):
        r2 = np.random.default_rng(seed)
        hist = [int(t) for t in r2.integers(0, cfg.vocab_size,
                                            size=(sys_len,))]
        (c,) = list(b.run([Request(np.asarray(hist, np.int32), new,
                                   session_id=sid)]))
        res_t, cold_t = [], []
        for _ in range(turns):
            hist += [int(t) for t in c.tokens]
            hist += [int(t) for t in r2.integers(0, cfg.vocab_size,
                                                 size=(user_len,))]
            prompt = np.asarray(hist, np.int32)
            (cold,) = list(b.run([Request(prompt, new)]))
            (c,) = list(b.run([Request(prompt, new, session_id=sid)]))
            if measure:
                assert c.tokens == cold.tokens, \
                    "resumed stream diverged from the cold reference"
                cold_t.append(1000.0 * cold.ttft_s)
                res_t.append(1000.0 * c.ttft_s)
        return res_t, cold_t

    conversation("prime", seed=98, measure=False)   # compiles only
    resumed_ttfts, cold_ttfts = conversation("bench", seed=99,
                                             measure=True)
    assert tier.stats()["resume"] >= 2 * turns, tier.stats()
    resumed_med = sorted(resumed_ttfts)[len(resumed_ttfts) // 2]
    cold_med = sorted(cold_ttfts)[len(cold_ttfts) // 2]
    assert resumed_med < cold_med, \
        (f"session resume-from-tier TTFT ({resumed_med:.2f}ms) not "
         f"below cold full-history prefill ({cold_med:.2f}ms)")

    # -- Part A2: the same contract through the FLEET front door on
    # the tiny CI model — resumed streams token-identical over the
    # wire, the tier counters aggregated off heartbeats into the
    # gateway's kv_tier gauge, and session affinity routing the turn
    # to the parker.  (Latency is asserted in part A where prefill
    # compute is measurable; fleet hops would drown a tiny model's.)
    fleet = FleetServer(replicas=replicas, rows=rows, tiny=True,
                        max_len=128, page_size=page, prefill_bucket=page,
                        kv_tier_mb=64, warmup=True, workers=workers,
                        max_queue=128, start_timeout=300.0)
    fleet.start()
    try:
        client = FleetClient(fleet.addr, fleet.token, timeout=300.0)
        hist = [int(t) for t in rng.integers(0, 97, size=(40,))]
        out = client.generate(np.asarray(hist, np.int32),
                              max_new_tokens, session="bench")
        for _ in range(turns):
            hist += [int(t) for t in out["tokens"]]
            hist += [int(t) for t in rng.integers(0, 97, size=(8,))]
            prompt = np.asarray(hist, np.int32)
            cold = client.generate(prompt, max_new_tokens)
            out = client.generate(prompt, max_new_tokens,
                                  session="bench")
            assert out["tokens"] == cold["tokens"], \
                "fleet resumed stream diverged from the cold reference"
        time.sleep(3.0 * fleet.heartbeat_interval + 0.2)
        kt = fleet.snapshot()["gauges"].get("kv_tier") or {}
        hits = kt.get("hits", 0)
        misses = kt.get("misses", 0)
        hit_rate = hits / max(1, hits + misses)
        assert kt.get("resume", 0) >= turns, \
            f"the fleet tier never resumed: {kt}"
        client.close()
    finally:
        fleet.stop()

    # -- Part B: the shared prefix as a fleet resource.
    system = rng.integers(0, 97, size=(2 * page,)).astype(np.int32)
    fleet = FleetServer(replicas=replicas, rows=rows, tiny=True,
                        max_len=96, page_size=page, prefill_bucket=page,
                        prefix_cache_pages=32, kv_tier_mb=64,
                        warmup=True, workers=workers, max_queue=128,
                        start_timeout=300.0)
    fleet.start()
    try:
        client = FleetClient(fleet.addr, fleet.token, timeout=300.0)

        def shared_prompt():
            return np.concatenate(
                [system, rng.integers(0, 97, size=(4,)).astype(np.int32)])

        # ONE priming request publishes the prefix somewhere; the next
        # heartbeat advertises it, and affinity steers everything else
        # there — the fleet prefills the common prompt exactly once.
        client.generate(shared_prompt(), max_new_tokens)
        time.sleep(3.0 * fleet.heartbeat_interval + 0.2)
        for _ in range(n_shared):
            client.generate(shared_prompt(), max_new_tokens)
        time.sleep(3.0 * fleet.heartbeat_interval + 0.2)
        stats = [(r.prefix or {}).get("stats") or {}
                 for r in fleet.registry.members()]
        prefills = sum(s.get("misses", 0) for s in stats)
        total_hits = sum(s.get("hits", 0) for s in stats)
        assert prefills == 1, \
            (f"the shared prefix must prefill ONCE per fleet "
             f"(router-directed seeding), saw {prefills} cold "
             f"prefills across {replicas} replicas: {stats}")
        assert total_hits >= n_shared, stats
        snap = fleet.snapshot()["counters"]
        ah = snap.get("affinity_hits", 0)
        am = snap.get("affinity_misses", 0)
        aff_rate = ah / max(1, ah + am)
        client.close()
    finally:
        fleet.stop()
    return resumed_med, cold_med, hit_rate, prefills, aff_rate


def bench_fleet_fabric(replicas=3, rows=2, workers=8, n_sessions=6,
                       max_new_tokens=4, n_transfers=24,
                       artifact_mb=1.0, seed=21):
    """The cross-host KV fabric (docs/SERVING.md "Cross-host KV
    fabric"), both halves asserted in-bench:

    * DIRECT vs RELAY streaming — the same artifact workload (seeded
      ~1 MB session blobs over raw HMAC frames) pushed straight to a
      peer's fabric surface versus through an intermediary hop (what
      the router-relay fallback costs: the body crosses the wire
      twice).  ``fleet_kv_transfer_mb_per_sec`` is the direct rate,
      asserted STRICTLY above ``fleet_kv_relay_mb_per_sec`` on the
      same workload.
    * HOST-LOSS-PROOF RESUME — a tiny fleet with ``--kv-replication
      2`` plus one dedicated ``--role kv`` holder: every park lands a
      replicated copy on the holder (kv-role peers are the preferred
      replica targets), the serving replica with the most parked
      primaries is SIGKILLed whole, and every session's next turn
      resumes on a survivor — the victim's primaries through a DIRECT
      fabric fetch of the holder's copy (the holder serves no
      generates, so affinity cannot shortcut the wire path) — with
      streams token-identical to a cold reference: ZERO lost sessions
      and at least one forwarded fetch hit, asserted in-bench.

    Reports (direct_mb_s, relay_mb_s, resumed_sessions,
    fabric_fetch_hits)."""
    from tfmesos_tpu.backends.local import LocalBackend
    from tfmesos_tpu.chaos import FaultPlan
    from tfmesos_tpu.fleet.client import FleetClient
    from tfmesos_tpu.fleet.kvtier import KVFabric, KVTierStore, fabric_rpc
    from tfmesos_tpu.fleet.launcher import FleetServer
    from tfmesos_tpu.fleet.replica import ReplicaServer, fabric_handler
    from tfmesos_tpu import wire

    rng = np.random.default_rng(seed)

    # -- Part A: direct peer streaming vs the relay fallback, on the
    # real wire stack.  The holder serves the fabric's kv_put/kv_fetch
    # surface; the relay re-ships every frame to it (one extra hop —
    # exactly the router-relay fallback's cost shape).
    token = "bench-fabric"
    body = rng.integers(0, 256, size=(int(artifact_mb * (1 << 20)),),
                        dtype=np.uint8).tobytes()
    store = KVTierStore(ram_bytes=max(4, 2 * n_transfers)
                        * len(body) + (64 << 20), token=token)
    holder = KVFabric(store, token=token, replication=1)
    hsrv = ReplicaServer(fabric_handler(holder), token=token).start()

    def relay(msg, reply):
        raw = isinstance(msg, wire.RawFrame)
        head = msg.meta if raw else msg
        reply(fabric_rpc(hsrv.addr, dict(head),
                         msg.body if raw else None, token=token,
                         timeout=60.0))

    rsrv = ReplicaServer(relay, token=token).start()

    def push_rate(addr, tag):
        fabric_rpc(addr, {"op": "kv_put", "kind": "session",
                          "key": f"{tag}-warm", "meta": {}}, body,
                   token=token, timeout=60.0)      # connection warmup
        t0 = time.perf_counter()
        for i in range(n_transfers):
            out = fabric_rpc(addr, {"op": "kv_put", "kind": "session",
                                    "key": f"{tag}-{i}", "meta": {}},
                             body, token=token, timeout=60.0)
            assert isinstance(out, dict) and out.get("op") == "kv_put_ok", \
                f"fabric push via {tag} failed: {out!r}"
        wall = time.perf_counter() - t0
        return n_transfers * len(body) / max(1e-9, wall) / (1 << 20)

    try:
        direct_mb_s = push_rate(hsrv.addr, "direct")
        relay_mb_s = push_rate(rsrv.addr, "relay")
    finally:
        rsrv.stop()
        hsrv.stop()
    assert direct_mb_s > relay_mb_s, \
        (f"direct peer streaming ({direct_mb_s:.1f} MB/s) not above "
         f"the relay fallback ({relay_mb_s:.1f} MB/s) on the same "
         f"workload — the extra hop must cost something")

    # -- Part B: replicated parking rides out a parker SIGKILL.
    plan = FaultPlan([], seed=seed)
    fleet = FleetServer(replicas=replicas, rows=rows, tiny=True,
                        max_len=128, page_size=16, prefill_bucket=16,
                        kv_tier_mb=64, kv_replication=2, kv_replicas=1,
                        warmup=True, workers=workers, max_queue=128,
                        request_timeout=300.0, start_timeout=300.0,
                        backend=LocalBackend(chaos=plan))
    fleet.start()
    try:
        client = FleetClient(fleet.addr, fleet.token, timeout=300.0)
        hists = {}
        for i in range(n_sessions):
            hist = [int(t) for t in rng.integers(0, 97, size=(24,))]
            out = client.generate(np.asarray(hist, np.int32),
                                  max_new_tokens, session=f"s{i}")
            hists[i] = hist + [int(t) for t in out["tokens"]]
        # Let the placement map fill: heartbeats advertise each tier's
        # parked sessions, and the replicated peer copies have landed
        # (the park ack waited for them).
        time.sleep(3.0 * fleet.heartbeat_interval + 0.2)
        # The victim is a SERVING replica (the kv holder carries every
        # replicated copy — killing it would test the wrong failure).
        serving = [r for r in fleet.registry.members()
                   if (r.role or "unified") != "kv"]
        victim = max(serving,
                     key=lambda r: len(((r.kv_tier or {})
                                        .get("sessions")) or []))
        n_primaries = len((victim.kv_tier or {}).get("sessions") or [])
        assert n_primaries >= 1, "no replica parked a session primary"
        assert plan.kill(victim.node), f"no pid for {victim.node}"
        deadline = time.perf_counter() + 300.0
        while victim.addr in [r.addr for r in fleet.registry.alive()]:
            assert time.perf_counter() < deadline, \
                "SIGKILLed parker never observed dead"
            time.sleep(0.05)
        # Every session's next turn must resume on a survivor — the
        # victim's primaries through a fabric fetch of the replicated
        # copy — and stream token-identical to a cold reference.
        lost = 0
        for i in range(n_sessions):
            hist = hists[i]
            hist += [int(t) for t in rng.integers(0, 97, size=(8,))]
            prompt = np.asarray(hist, np.int32)
            cold = client.generate(prompt, max_new_tokens)
            res = client.generate(prompt, max_new_tokens,
                                  session=f"s{i}")
            if res["tokens"] != cold["tokens"]:
                lost += 1
        time.sleep(3.0 * fleet.heartbeat_interval + 0.2)
        kt = fleet.snapshot()["gauges"].get("kv_tier") or {}
        resumed = kt.get("resume", 0)
        fetch_hits = kt.get("fabric_fetch_hit", 0)
        # The survivors served every post-kill turn, so their resume
        # counters alone must cover all n_sessions — a session whose
        # artifact died with its host would cold-prefill instead and
        # never count here.
        lost += max(0, n_sessions - resumed)
        assert lost == 0, \
            (f"{lost} of {n_sessions} sessions lost across the parker "
             f"SIGKILL (resumed={resumed}, tier={kt})")
        assert fetch_hits >= 1, \
            (f"no fabric fetch served a forwarded resume — the "
             f"victim held {n_primaries} primaries: {kt}")
        client.close()
    finally:
        fleet.stop()
    return direct_mb_s, relay_mb_s, n_sessions, fetch_hits


def bench_serving_longctx(n_requests=8, rows=4, max_len=8192,
                          plen=512, new=128, tiny=False):
    """Continuous batching at LONG context — the regime the kernel-native
    carried cache, bucketed decode tables, and deferred pool commits
    were built for (an 8k-slot paged pool per row).  Reports generated
    tokens/s across the stream and mean TTFT, with multi_step=16 +
    overlap (the production setting); same protocol/scaffolding as the
    headline serving bench (``_serving_bench_setup``; ``tiny=True`` is
    the CI smoke — same call path at toy sizes)."""
    from tfmesos_tpu.serving import ContinuousBatcher

    cfg, params, reqs, max_len, new = _serving_bench_setup(
        tiny, max_len=max_len, plen=plen, new=new)
    b = ContinuousBatcher(cfg, params, rows=rows, max_len=max_len,
                          multi_step=2 if tiny else 16, overlap=True)
    list(b.run(reqs(2)))    # warm the compiles outside the timed region
    t0 = time.perf_counter()
    done = list(b.run(reqs(n_requests)))
    dt = time.perf_counter() - t0
    assert len(done) == n_requests
    ttft = 1000.0 * sum(c.ttft_s for c in done) / n_requests
    return n_requests * new / dt, ttft


def bench_serving_continuous_mesh(n_requests=32, rows=8, tiny=False):
    """Multi-chip continuous serving: the same stream through a dp x tp
    mesh over every visible device (pool pages sharded over dp, heads
    over tp) — requests/s should scale with dp on real slices.  Its own
    bench section so a mesh failure cannot discard the single-device
    serving numbers."""
    import jax
    from tfmesos_tpu.parallel.mesh import build_mesh
    from tfmesos_tpu.serving import ContinuousBatcher

    n = jax.device_count()
    if n < 2:
        return None
    cfg, params, reqs, max_len, _ = _serving_bench_setup(tiny)
    tp = 2 if cfg.n_heads % 2 == 0 and n % 2 == 0 else 1
    dp = n // tp
    mesh = build_mesh({"dp": dp, "tp": tp},
                      devices=jax.devices()[:dp * tp])
    mrows = -(-rows // dp) * dp         # smallest multiple of dp >= rows
    mb = ContinuousBatcher(cfg, params, rows=mrows, max_len=max_len,
                           mesh=mesh)
    list(mb.run(reqs(2)))   # warm the compiles outside the timed region
    t0 = time.perf_counter()
    done = list(mb.run(reqs(n_requests)))
    dt = time.perf_counter() - t0
    assert len(done) == n_requests
    return n_requests / dt


def bench_fleet_serving(n_requests=32, replicas=2, rows=4, tiny=True,
                        max_new_tokens=8, workers=16):
    """Online fleet serving: requests/s and mean TTFT through the full
    front door — gateway + admission + router + N ``LocalBackend``
    CPU replicas (co-located replicas cannot share one TPU, so the
    multi-replica path is measured on CPU; what this metric tracks is
    the FLEET overhead trajectory — wire hops, routing, admission —
    on top of the per-replica serving numbers above).  The model is the
    tiny CI config by default: fleet costs are model-independent, and a
    flagship-on-CPU replica would measure XLA CPU, not the gateway."""
    import threading

    from tfmesos_tpu.fleet.client import FleetClient
    from tfmesos_tpu.fleet.launcher import FleetServer

    rng = np.random.default_rng(0)
    fleet = FleetServer(replicas=replicas, rows=rows, tiny=tiny,
                        max_len=64 if tiny else None,
                        page_size=16 if tiny else None,
                        prefill_bucket=16 if tiny else None,
                        workers=workers,
                        max_queue=max(64, 2 * n_requests),
                        start_timeout=300.0)
    fleet.start()
    try:
        client = FleetClient(fleet.addr, fleet.token, timeout=300.0)

        def run_batch(n):
            # Prompts come from the main thread: numpy Generators are
            # not thread-safe, and the workers only send/wait.
            prompts = [rng.integers(0, 97, size=(8,)).astype(np.int32)
                       for _ in range(n)]
            results = [None] * n

            def one(i):
                results[i] = client.generate(prompts[i], max_new_tokens)

            threads = [threading.Thread(target=one, args=(i,))
                       for i in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return results

        # Warm every replica's compile outside the timed region: with
        # least-outstanding routing, 2*replicas concurrent requests
        # land on every replica.
        run_batch(2 * replicas)
        t0 = time.perf_counter()
        results = run_batch(n_requests)
        dt = time.perf_counter() - t0
        done = [r for r in results if r is not None]
        assert len(done) == n_requests
        ttft = sum(r["ttft_ms"] for r in done) / len(done)
        # Admission-queue wait is its OWN histogram (never folded into
        # TTFT): report its p50 AND p99 — the autoscaler keys off the
        # p99 tail, so the signal scaling reacts to must be a
        # first-class observable, not a median that hides the stalls.
        qw = fleet.snapshot()["histograms"].get("queue_wait_ms", {})
        client.close()
        return (n_requests / dt, ttft, qw.get("p50", 0.0),
                qw.get("p99", 0.0))
    finally:
        fleet.stop()


def bench_fleet_disagg(n_decode=8, decode_new=24, prompt_len=96,
                       rows=4, workers=8, feeders=2):
    """Disaggregated prefill/decode serving vs a unified fleet of the
    SAME size on a mixed workload: long-prompt requests stream in
    continuously (the feeder threads) while long-decode requests
    measure inter-token latency.  In a unified replica every admitted
    long prefill stalls the co-resident decode ticks for its whole
    prompt; with dedicated tiers the decode replica only ever imports
    KV pages (one scatter) and decodes — the p50 inter-token gap of the
    decode-heavy requests is the headline, and must be strictly better
    disaggregated.  Also reports end-to-end TTFT per mode and the
    KV-transfer throughput of the prefill→decode handoff."""
    import threading

    from tfmesos_tpu.fleet.client import FleetClient
    from tfmesos_tpu.fleet.launcher import FleetServer

    page = 16
    rng = np.random.default_rng(2)
    decode_prompts = [rng.integers(0, 97, size=(8,)).astype(np.int32)
                      for _ in range(n_decode)]
    long_pool = [rng.integers(0, 97, size=(prompt_len,)).astype(np.int32)
                 for _ in range(32)]

    def run_fleet(**kw):
        fleet = FleetServer(rows=rows, tiny=True, max_len=128,
                            page_size=page, prefill_bucket=page,
                            workers=workers, max_queue=256,
                            request_timeout=300.0,
                            start_timeout=300.0, **kw)
        fleet.start()
        try:
            client = FleetClient(fleet.addr, fleet.token, timeout=300.0)
            # Warm both request shapes' compiles outside the timed
            # region (prefill bucket of the long prompts, and decode).
            client.generate(long_pool[0], 2)
            client.generate(decode_prompts[0], 2)
            stop = threading.Event()
            feed_errors = []

            def feeder(k):
                i = 0
                streak = 0
                while not stop.is_set():
                    try:
                        client.generate(
                            long_pool[(k * 13 + i) % len(long_pool)], 2,
                            timeout=300.0)
                        streak = 0
                    except Exception as e:
                        if stop.is_set():
                            return
                        # A transient shed or heartbeat flap must not
                        # silently remove the interference load — the
                        # headline dis_itl < uni_itl comparison is only
                        # meaningful while BOTH runs see continuous long
                        # prefills.  Keep feeding; only a persistent
                        # streak aborts the bench loudly (asserted after
                        # join, not swallowed in a daemon thread).
                        streak += 1
                        if streak >= 8:
                            feed_errors.append(e)
                            return
                        time.sleep(0.05)
                    i += 1

            results = [None] * n_decode

            def one(i):
                results[i] = client.generate(decode_prompts[i],
                                             decode_new, timeout=300.0)

            fthreads = [threading.Thread(target=feeder, args=(k,),
                                         daemon=True)
                        for k in range(feeders)]
            t0 = time.perf_counter()
            for f in fthreads:
                f.start()
            time.sleep(0.05)    # let long prefills be in flight first
            threads = [threading.Thread(target=one, args=(i,))
                       for i in range(n_decode)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            stop.set()
            for f in fthreads:
                f.join(timeout=300.0)
            snap = fleet.snapshot()
            client.close()
            assert not feed_errors, \
                f"interference feeder died mid-run: {feed_errors[0]!r}"
            assert all(r is not None for r in results)
            return results, snap, wall
        finally:
            fleet.stop()

    uni_res, _, _ = run_fleet(replicas=2)
    dis_res, dis_snap, dis_wall = run_fleet(replicas=0,
                                            prefill_replicas=1,
                                            decode_replicas=1)

    def itl_p50(rs, disagg):
        vals = sorted(
            (r["decode_ms"] if disagg else r["total_ms"] - r["ttft_ms"])
            / max(1, decode_new - 1) for r in rs)
        return vals[len(vals) // 2]

    uni_itl = itl_p50(uni_res, False)
    dis_itl = itl_p50(dis_res, True)
    uni_ttft = sum(r["ttft_ms"] for r in uni_res) / len(uni_res)
    dis_ttft = sum(r["ttft_ms"] for r in dis_res) / len(dis_res)
    c = dis_snap["counters"]
    # Both tiers must actually have served: every request crossed
    # prefill → transfer → decode (the roles gauge shows the tiers).
    assert c.get("disagg_prefills", 0) > 0, "prefill tier never served"
    assert c.get("disagg_decodes", 0) > 0, "decode tier never served"
    roles = dis_snap["gauges"].get("roles") or {}
    assert roles.get("prefill", {}).get("alive"), roles
    assert roles.get("decode", {}).get("alive"), roles
    assert dis_itl < uni_itl, \
        (f"disaggregated decode inter-token p50 {dis_itl:.2f}ms not "
         f"better than unified {uni_itl:.2f}ms — prefill stalls leaked "
         f"into the decode tier")
    kv_mb_s = c.get("kv_transfer_bytes", 0) / 1e6 / dis_wall
    return dis_ttft, dis_itl, uni_ttft, uni_itl, kv_mb_s


def bench_fleet_gang(n_requests=6, gang_size=2, rows=4, decode_new=24,
                     workers=8):
    """Gang replicas (docs/SERVING.md "Gang replicas") behind the same
    gateway: each replica is ``gang_size`` member tasks forming one
    leader-coordinated mesh, routed as ONE ``ReplicaInfo``.  Three
    phases on LocalBackend CPU gangs:

    * token identity + inter-token p50 — the SAME greedy-decode prompts
      stream through a ``gang_size``-member gang fleet and a
      single-process fleet; every stream is asserted token-identical
      (the leader owns sampling; members mirror-execute and digest-ack),
      and ``fleet_gang_itl_p50_ms`` vs ``fleet_single_itl_p50_ms``
      tracks the leader's dispatch fan-out overhead (on CPU the members
      add no compute — real slices flip the comparison).
    * ``fleet_gang_reform_s`` — SIGKILL one MEMBER task mid-decode: the
      gang dies whole (member death = gang death), in-flight work fails
      over to the surviving gang via router replay (zero lost requests
      asserted, streams still token-identical), and the launcher
      re-forms the gang under a fresh generation; the number is
      kill -> both replicas routable again.
    * gang drain-migration — a pinned drain + migrate of a busy gang
      mid-decode must move its in-flight work losslessly (zero lost,
      token-identical), exactly like a single-process replica's drain.
    """
    import threading

    from tfmesos_tpu.chaos import FaultPlan
    from tfmesos_tpu.fleet.client import FleetClient
    from tfmesos_tpu.fleet.launcher import FleetServer
    from tfmesos_tpu.backends.local import LocalBackend

    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, 97, size=(8,)).astype(np.int32)
               for _ in range(n_requests)]

    def itl_p50(rs):
        vals = sorted((r["total_ms"] - r["ttft_ms"])
                      / max(1, decode_new - 1) for r in rs)
        return vals[len(vals) // 2]

    def run_single():
        fleet = FleetServer(replicas=1, rows=rows, tiny=True, max_len=64,
                            page_size=16, prefill_bucket=16,
                            workers=workers, max_queue=256,
                            request_timeout=300.0, start_timeout=300.0)
        fleet.start()
        try:
            client = FleetClient(fleet.addr, fleet.token, timeout=300.0)
            client.generate(prompts[0], 2)      # warm the compile
            res = [client.generate(p, decode_new, timeout=300.0)
                   for p in prompts]
            client.close()
            return res
        finally:
            fleet.stop()

    single_res = run_single()
    single_itl = itl_p50(single_res)

    plan = FaultPlan([], seed=5)
    fleet = FleetServer(replicas=2, gang_size=gang_size, rows=rows,
                        tiny=True, max_len=64, page_size=16,
                        prefill_bucket=16, workers=workers, max_queue=256,
                        request_timeout=300.0, start_timeout=300.0,
                        backend=LocalBackend(chaos=plan))
    fleet.start()
    try:
        client = FleetClient(fleet.addr, fleet.token, timeout=300.0)

        def run_batch(reqs, results, errors):
            def one(i):
                try:
                    results[i] = client.generate(reqs[i], decode_new,
                                                 timeout=300.0)
                except Exception as e:
                    errors.append((i, e))
            threads = [threading.Thread(target=one, args=(i,),
                                        daemon=True)
                       for i in range(len(reqs))]
            for t in threads:
                t.start()
            return threads

        # Warm BOTH gangs' compiles: with least-outstanding routing,
        # 2*replicas concurrent requests land on every gang.
        warm = [None] * 4
        for t in run_batch([prompts[0]] * 4, warm, []):
            t.join(timeout=300.0)

        gang_res = [client.generate(p, decode_new, timeout=300.0)
                    for p in prompts]
        for i, (g, s) in enumerate(zip(gang_res, single_res)):
            assert g["tokens"] == s["tokens"], \
                (f"gang stream {i} diverged from the single-host "
                 f"reference: {g['tokens']} vs {s['tokens']}")
        gang_itl = itl_p50(gang_res)

        # --- phase 2: SIGKILL one gang MEMBER mid-decode -------------
        with fleet._gang_lock:
            gangs = dict(fleet._gangs)
        assert len(gangs) == 2, f"expected 2 gangs, got {list(gangs)}"
        gid, info = sorted(gangs.items())[0]
        member_node = None
        for t in fleet.scheduler.tasks_of("replica"):
            node = f"{t.job_name}:{t.task_index}"
            if getattr(t, "gang", None) == gid \
                    and node != info["leader_node"]:
                member_node = node
        assert member_node is not None, f"gang {gid} has no member task"

        old_addrs = {r.addr for r in fleet.registry.alive()}
        results = [None] * n_requests
        errors = []
        threads = run_batch(prompts, results, errors)
        deadline = time.perf_counter() + 120.0
        while time.perf_counter() < deadline:
            if any(r.outstanding > 0 for r in fleet.registry.alive()):
                break
            time.sleep(0.01)
        t_kill = time.perf_counter()
        plan.kill(member_node)
        # Re-formed means a FRESH leader addr is routable again — the
        # dead gang's leader lingers in alive() until the registry sees
        # its heartbeat drop, so counting addrs alone would read the
        # pre-kill fleet as already re-formed.
        reform_s = None
        deadline = time.perf_counter() + 300.0
        while time.perf_counter() < deadline:
            addrs = {r.addr for r in fleet.registry.alive()}
            if len(addrs) == 2 and addrs - old_addrs:
                reform_s = time.perf_counter() - t_kill
                break
            time.sleep(0.05)
        assert reform_s is not None, "gang never re-formed after the kill"
        for t in threads:
            t.join(timeout=300.0)
        assert not errors, \
            f"request lost across gang-member kill: {errors[0]!r}"
        for i, r in enumerate(results):
            assert r is not None, f"request {i} never completed"
            assert r["tokens"] == single_res[i]["tokens"], \
                f"stream {i} diverged across gang failover"
        c = fleet.snapshot()["counters"]
        assert c.get("gang_reforms", 0) >= 1, \
            f"launcher never re-formed the gang: {c}"

        # --- phase 3: drain-migrate a busy gang ----------------------
        # Warm the re-formed gang's compile first (least-outstanding
        # routing lands concurrent requests on it), so the drain's
        # suspended work has a live, warm candidate to resume on.
        warm2 = [None] * 4
        for t in run_batch([prompts[0]] * 4, warm2, []):
            t.join(timeout=300.0)
        results2 = [None] * n_requests
        errors2 = []
        threads2 = run_batch(prompts, results2, errors2)
        victim = None
        deadline = time.perf_counter() + 120.0
        while victim is None and time.perf_counter() < deadline:
            busy = [r for r in fleet.registry.alive()
                    if r.outstanding > 0]
            victim = busy[0].addr if busy else None
            time.sleep(0.01)
        assert victim is not None, "no gang ever reported work"
        assert fleet.registry.begin_drain(victim, pinned=True)
        fleet.request_migration(victim)
        for t in threads2:
            t.join(timeout=300.0)
        assert not errors2, \
            f"request lost in gang drain-migration: {errors2[0]!r}"
        for i, r in enumerate(results2):
            assert r is not None, f"drained request {i} never completed"
            assert r["tokens"] == single_res[i]["tokens"], \
                f"stream {i} diverged across gang drain-migration"
        client.close()
    finally:
        fleet.stop()
    return gang_itl, single_itl, reform_s


def bench_fleet_autoscale(rows=2, max_new_tokens=4, workers=8):
    """Control-plane reaction benchmarks on a live LocalBackend fleet:

    * ``fleet_scaleup_reaction_s`` — surge start → a NEW replica task
      launched by the autoscaler is registered and ROUTABLE.  The surge
      is an injected signal (the chaos.py discipline: the bench
      measures the fleet's launch→register→alive pipeline, not signal
      plumbing) and the loop is stepped by hand, so the number is the
      actuation cost, deterministically triggered.
    * ``fleet_rollout_downtime_ms`` — a blue-green rollout to a new
      weights_version runs under CONTINUOUS traffic; every request must
      succeed (zero Overloaded, zero RoutingError — asserted), so the
      recorded downtime is 0 by contract and the bench fails loudly the
      day it is not.
    """
    import threading

    from tfmesos_tpu.fleet.autoscaler import (AutoscalerConfig,
                                              FleetAutoscaler)
    from tfmesos_tpu.fleet.client import FleetClient
    from tfmesos_tpu.fleet.launcher import FleetServer

    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, 97, size=(8,)).astype(np.int32)
               for _ in range(16)]
    fleet = FleetServer(replicas=1, rows=rows, tiny=True, max_len=64,
                        page_size=16, prefill_bucket=16, workers=workers,
                        max_queue=256, min_replicas=1, max_replicas=2,
                        request_timeout=300.0, start_timeout=300.0)
    fleet.start()
    try:
        client = FleetClient(fleet.addr, fleet.token, timeout=300.0)
        client.generate(prompts[0], 2)      # warm the compile

        def alive():
            return fleet.registry.role_summary().get(
                "unified", {}).get("alive", 0)

        # Hand-stepped control loop over an injected signal source.
        surge = {"queue_wait_p99_ms": 10_000.0, "util": 1.0,
                 "kv_headroom": None}
        calm = {"queue_wait_p99_ms": 0.0, "util": 0.0,
                "kv_headroom": None}
        sig = {"unified": surge}
        auto = FleetAutoscaler(
            fleet, AutoscalerConfig(scale_up_cooldown=0.0,
                                    scale_down_cooldown=0.0,
                                    drain_grace=0.2),
            signals=lambda: dict(sig))
        t0 = time.perf_counter()
        deadline = t0 + 300.0
        while alive() < 2:
            if time.perf_counter() > deadline:
                raise RuntimeError("autoscaled replica never routable")
            auto.step()
            time.sleep(0.05)
        reaction_s = time.perf_counter() - t0
        # Decay: the loop drains the least-loaded replica and kills it
        # only after its outstanding work flushed.
        sig["unified"] = calm
        while fleet.tier_actual("unified") > 1:
            if time.perf_counter() > deadline:
                raise RuntimeError("scale-down drain never completed")
            auto.step()
            time.sleep(0.05)

        # Blue-green rollout under continuous traffic.
        stop = threading.Event()
        failures = []

        def feeder():
            i = 0
            while not stop.is_set():
                try:
                    client.generate(prompts[i % len(prompts)],
                                    max_new_tokens, timeout=300.0)
                except Exception as e:
                    failures.append(e)
                    return
                i += 1

        th = threading.Thread(target=feeder, daemon=True)
        th.start()
        time.sleep(0.2)                 # traffic in flight first
        fleet.rollout("v2", bake_s=0.5)
        stop.set()
        th.join(timeout=300.0)
        client.close()
        assert not failures, \
            f"rollout failed/shed a request: {failures[0]!r}"
        versions = fleet.registry.role_summary().get(
            "unified", {}).get("versions", {})
        assert list(versions) == ["v2"], versions
        return reaction_s, 0.0
    finally:
        fleet.stop()


def bench_fleet_multimodel(rows=2, max_new_tokens=4, workers=8):
    """Many models, one fleet (docs/SERVING.md "Model catalog") on a
    live LocalBackend fleet, every contract asserted in-bench:

    * ``fleet_multimodel_trade_reaction_s`` — a two-model hotness flip
      on a FIXED replica budget: the hand-stepped ModelTrader (injected
      signals, the chaos.py discipline — the bench measures the
      drain→launch→register→alive pipeline, not signal plumbing) must
      TRADE a cold model's replica away and stand the hot model's
      second replica up; continuous two-tenant traffic rides through
      the whole trade with ZERO failed/shed requests (asserted).
    * ``fleet_multimodel_pool_cold_start_ttft_ms`` vs ``..._relaunch_
      cold_start_ttft_ms`` — a scale-to-zero model's FIRST request:
      warm-pool adoption (a weight install on a pre-warmed,
      pre-compiled process) vs the pool-exhausted path (trade a slot +
      cold process launch + compile); pool STRICTLY below relaunch
      asserted.
    * ``fleet_multimodel_swap_ms`` — ``swap_adapter`` under continuous
      traffic: every request during the swap is SERVED (zero
      downtime), every stream equals exactly ONE delta version's
      reference (token-identical per version — never a mix), and
      every request submitted after the fleet-wide ack streams the NEW
      version.
    * billing-grade metering: ``metering_{prompt,decode}_tokens_
      <tenant>_<model>`` counters present for every pair that carried
      traffic (they ride the snapshot AND the Prometheus exposition).
    """
    import threading

    from tfmesos_tpu.fleet.admission import PriorityClass
    from tfmesos_tpu.fleet.autoscaler import AutoscalerConfig
    from tfmesos_tpu.fleet.catalog import (ModelSpec, ModelTrader,
                                           TraderConfig, model_key)
    from tfmesos_tpu.fleet.client import FleetClient
    from tfmesos_tpu.fleet.launcher import FleetServer
    from tfmesos_tpu.fleet.replica import tiny_model

    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, 97, size=(6,)).astype(np.int32)
               for _ in range(8)]
    fleet = FleetServer(
        models=[ModelSpec("alpha", replicas=2, seed=0),
                ModelSpec("beta", replicas=1, seed=1),
                ModelSpec("gamma", replicas=0, seed=2),
                ModelSpec("delta", replicas=0, seed=3)],
        warm_pool=1, tiny=True, rows=rows, workers=workers,
        max_queue=256,
        priority_classes=[PriorityClass("tenantA", weight=2.0, rank=1),
                          PriorityClass("tenantB", weight=1.0, rank=0)],
        request_timeout=300.0, start_timeout=300.0)
    fleet.start()
    out = {}
    try:
        # The built-in trader thread would race the hand-stepped one
        # below; its demand hook (the router's cold-start path) stays
        # live — stopping the thread stops the TICKS, not the surface.
        fleet.trader.stop()
        client = FleetClient(fleet.addr, fleet.token, timeout=300.0)
        for model, tenant in (("alpha", "tenantA"), ("beta", "tenantB")):
            client.generate(prompts[0], 2, model=model,
                            priority=tenant)     # warm the compiles

        def alive(model):
            return [r for r in fleet.registry.members(model=model)
                    if r.state == "alive"]

        # -- cold start #1: through the warm pool (a weight install).
        # The pool slot is the budget's only slack, so this must come
        # FIRST — once it is consumed, every later reallocation is a
        # genuine trade.
        t0 = time.perf_counter()
        client.generate(prompts[1], max_new_tokens, model="gamma",
                        priority="tenantA")
        pool_ttft_ms = 1000.0 * (time.perf_counter() - t0)
        assert fleet.metrics.get("model_adoptions") == 1
        # Wait out the adopter's identity flip (heartbeat-lagged) so
        # a later demand cannot see a stale pool member.
        deadline = time.perf_counter() + 60.0
        while fleet.registry.has_pool():
            if time.perf_counter() > deadline:
                raise RuntimeError("adopted replica still advertises "
                                   "warm_pool")
            time.sleep(0.05)

        # -- the hotness flip, under continuous two-tenant traffic.
        # Dead-band signals on alpha, beta HOT, budget full, pool
        # gone: the ONLY way beta can grow is a TRADE — alpha (the
        # sole model above its live bound) drain-MIGRATES one replica
        # away mid-traffic and beta's second one launches in its slot.
        DEAD = {"queue_wait_p99_ms": 100.0, "util": 0.4, "samples": 5}
        sig = {model_key("alpha"): dict(DEAD),
               model_key("beta"): dict(DEAD)}
        trader = ModelTrader(
            fleet, fleet.catalog,
            AutoscalerConfig(scale_up_cooldown=0.0,
                             scale_down_cooldown=0.0, drain_grace=0.2),
            trader_config=TraderConfig(trade_cooldown_s=0.2,
                                       zero_after_ticks=10 ** 6),
            signals=lambda: {k: dict(v) for k, v in sig.items()})
        stop = threading.Event()
        failures = []

        def feeder(model, tenant):
            i = 0
            while not stop.is_set():
                try:
                    client.generate(prompts[i % len(prompts)],
                                    max_new_tokens, model=model,
                                    priority=tenant, timeout=300.0)
                except Exception as e:
                    failures.append(e)
                    return
                i += 1

        threads = [threading.Thread(target=feeder, args=a, daemon=True)
                   for a in (("alpha", "tenantA"), ("beta", "tenantB"))]
        for th in threads:
            th.start()
        time.sleep(0.4)                  # traffic in flight first
        sig[model_key("beta")] = {"queue_wait_p99_ms": 10_000.0,
                                  "util": 1.0, "samples": 50}
        t0 = time.perf_counter()
        deadline = t0 + 300.0
        while len(alive("beta")) < 2:
            if time.perf_counter() > deadline:
                raise RuntimeError("traded beta replica never routable")
            trader.step()
            time.sleep(0.05)
        reaction_s = time.perf_counter() - t0
        assert fleet.metrics.get("model_trades") >= 1
        # Converge the trade's victim side: alpha's drained replica
        # migrates its in-flight rows (the feeder keeps hammering it)
        # and is reaped — lossless, per the feeder assertion below.
        sig[model_key("beta")] = dict(DEAD)
        while fleet.tier_actual(model_key("alpha")) > 1:
            if time.perf_counter() > deadline:
                raise RuntimeError("traded-away replica never reaped")
            trader.step()
            time.sleep(0.05)

        # -- cold start #2: pool exhausted — the demand must TRADE a
        # slot from a cold model (beta, the only one above its live
        # bound now) and cold-LAUNCH a process (fork + jax import +
        # compile): the expensive path the warm pool exists to avoid.
        t0 = time.perf_counter()
        client.generate(prompts[2], max_new_tokens, model="delta",
                        priority="tenantB")
        relaunch_ttft_ms = 1000.0 * (time.perf_counter() - t0)
        assert pool_ttft_ms < relaunch_ttft_ms, \
            (f"warm-pool cold start ({pool_ttft_ms:.0f}ms) not below "
             f"cold relaunch ({relaunch_ttft_ms:.0f}ms)")

        # -- adapter hot-swap under the same continuous traffic.
        cfg_t, params_t = tiny_model(1)      # beta's preset (seed 1)
        embed = np.asarray(params_t["embed"])
        delta = {"embed": (0.5 * np.random.default_rng(9)
                           .standard_normal(embed.shape)
                           ).astype(embed.dtype)}
        probe = prompts[3]
        ref_old = client.generate(probe, max_new_tokens, model="beta",
                                  priority="tenantB")["tokens"]
        swap_records = []
        swap_stop = threading.Event()

        def swap_feeder():
            while not swap_stop.is_set():
                t_submit = time.perf_counter()
                try:
                    r = client.generate(probe, max_new_tokens,
                                        model="beta",
                                        priority="tenantB",
                                        timeout=300.0)
                except Exception as e:
                    failures.append(e)
                    return
                swap_records.append((t_submit, r["tokens"]))

        th_swap = threading.Thread(target=swap_feeder, daemon=True)
        th_swap.start()
        time.sleep(0.3)
        t0 = time.perf_counter()
        client.swap_adapter("beta", "lora1", delta)
        t_ack = time.perf_counter()
        swap_ms = 1000.0 * (t_ack - t0)
        time.sleep(0.5)                 # post-ack traffic
        swap_stop.set()
        stop.set()
        th_swap.join(timeout=300.0)
        for th in threads:
            th.join(timeout=300.0)
        assert not failures, \
            f"lost/shed a request across trade or swap: {failures[0]!r}"
        ref_new = client.generate(probe, max_new_tokens, model="beta",
                                  priority="tenantB")["tokens"]
        assert ref_new != ref_old, \
            "adapter delta did not change the stream (delta too small)"
        for t_submit, toks in swap_records:
            assert toks in (ref_old, ref_new), \
                f"stream matches NEITHER delta version: {toks}"
            if t_submit > t_ack:
                assert toks == ref_new, \
                    "request submitted after the swap ack streamed the "\
                    "OLD delta version"
        assert any(r.adapter_version == "lora1"
                   for r in alive("beta")), "adapter_version never "\
            "rode a heartbeat into the registry"

        # -- billing-grade per-tenant x model metering.
        counters = client.metrics()["counters"]
        for tenant, model in (("tenantA", "alpha"), ("tenantB", "beta"),
                              ("tenantA", "gamma"),
                              ("tenantB", "delta")):
            for kind in ("prompt", "decode"):
                key = f"metering_{kind}_tokens_{tenant}_{model}"
                assert counters.get(key, 0) > 0, f"no meter {key}"
        client.close()
        out = {
            "fleet_multimodel_trade_reaction_s": round(reaction_s, 2),
            "fleet_multimodel_pool_cold_start_ttft_ms":
                round(pool_ttft_ms, 1),
            "fleet_multimodel_relaunch_cold_start_ttft_ms":
                round(relaunch_ttft_ms, 1),
            "fleet_multimodel_swap_ms": round(swap_ms, 1),
            "fleet_multimodel_lost_requests": len(failures),
            "fleet_multimodel_metered_pairs": sum(
                1 for k in counters
                if k.startswith("metering_prompt_tokens_")),
        }
        return out
    finally:
        fleet.stop()


def bench_fleet_priority(n_interactive=16, rows=3, workers=8,
                         flood_threads=3, interactive_new=2,
                         background_new=24):
    """SLO isolation + lossless migration under churn, on a live
    two-replica CPU fleet with priority classes and drain migration:

    * ``fleet_priority_p99_ttft_ms`` vs ``fleet_background_p99_ttft_ms``
      — client-observed completion latency p99 of short (TTFT-
      dominated) interactive requests while ``flood_threads`` background
      feeders saturate the fleet with long decodes, vs the flooding
      tenant's own p99.  WFQ admission + in-batcher preemption are what
      hold the first flat: asserted within 1.5x of its UNLOADED value
      (with a small absolute epsilon — at the CPU smoke scale the whole
      latency is tens of ms, where one scheduler hiccup outweighs any
      real queueing effect), and strictly below the background p99.
    * ``fleet_migration_lost_requests`` — failed requests across an
      autoscaler-style scale-down (pinned drain → migrate → kill) AND a
      blue-green rollout, both under continuous two-class traffic with
      drain migration on.  Asserted ZERO: suspended rows resume
      elsewhere mid-stream, requeued work re-runs deterministically.
    """
    import threading

    from tfmesos_tpu.fleet.admission import PriorityClass
    from tfmesos_tpu.fleet.autoscaler import (AutoscalerConfig,
                                              FleetAutoscaler)
    from tfmesos_tpu.fleet.client import FleetClient
    from tfmesos_tpu.fleet.launcher import FleetServer

    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, 97, size=(8,)).astype(np.int32)
               for _ in range(16)]
    classes = [PriorityClass("interactive", weight=8.0, rank=1),
               PriorityClass("background", weight=1.0, rank=0,
                             max_queue=2 * flood_threads)]
    fleet = FleetServer(replicas=2, rows=rows, tiny=True, max_len=64,
                        page_size=16, prefill_bucket=16, workers=workers,
                        max_queue=256, priority_classes=classes,
                        min_replicas=1, max_replicas=2,
                        request_timeout=300.0, start_timeout=300.0)
    fleet.start()
    try:
        client = FleetClient(fleet.addr, fleet.token, timeout=300.0)
        client.generate(prompts[0], 2)          # warm the compiles
        client.generate(prompts[1], background_new)

        p99 = _p99

        def timed_batch(n, priority):
            walls = []
            for i in range(n):
                t0 = time.perf_counter()
                client.generate(prompts[i % len(prompts)],
                                interactive_new, priority=priority,
                                timeout=300.0)
                walls.append((time.perf_counter() - t0) * 1000.0)
            return walls

        # Phase 1: unloaded interactive latency (sequential, warm).
        unloaded_p99 = p99(timed_batch(n_interactive, "interactive"))

        # Phase 2: the background tenant floods every row with long
        # decodes while the interactive tenant keeps its cadence.
        stop = threading.Event()
        bg_walls, bg_errors = [], []
        bg_lock = threading.Lock()

        def flood(k):
            i = 0
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    client.generate(prompts[(k * 7 + i) % len(prompts)],
                                    background_new,
                                    priority="background",
                                    timeout=300.0)
                    with bg_lock:
                        bg_walls.append(
                            (time.perf_counter() - t0) * 1000.0)
                except Exception as e:
                    # Background sheds are the DESIGN under flood (its
                    # class queue is bounded); anything else is a bug.
                    if "overloaded" not in repr(e).lower() \
                            and not stop.is_set():
                        bg_errors.append(e)
                        return
                    time.sleep(0.01)
                i += 1

        floods = [threading.Thread(target=flood, args=(k,), daemon=True)
                  for k in range(flood_threads)]
        for f in floods:
            f.start()
        time.sleep(0.3)             # flood in flight first
        loaded = timed_batch(n_interactive, "interactive")
        loaded_p99 = p99(loaded)
        stop.set()
        for f in floods:
            f.join(timeout=300.0)
        assert not bg_errors, \
            f"background feeder failed mid-flood: {bg_errors[0]!r}"
        assert bg_walls, "flood never completed a request"
        bg_p99 = p99(bg_walls)
        assert loaded_p99 <= max(1.5 * unloaded_p99,
                                 unloaded_p99 + 150.0), \
            (f"interactive p99 {loaded_p99:.1f}ms not held within 1.5x "
             f"of unloaded {unloaded_p99:.1f}ms under background flood")
        assert loaded_p99 < bg_p99, \
            (f"class isolation failed: interactive p99 {loaded_p99:.1f}"
             f"ms >= background p99 {bg_p99:.1f}ms")

        # Phase 3: zero lost requests across scale-down + rollout with
        # drain migration on, under continuous gentle two-class traffic.
        stop = threading.Event()
        failures = []

        def feeder(priority):
            i = 0
            while not stop.is_set():
                try:
                    client.generate(prompts[i % len(prompts)],
                                    background_new, priority=priority,
                                    timeout=300.0)
                except Exception as e:
                    if not stop.is_set():
                        failures.append(e)
                    return
                i += 1

        feeders = [threading.Thread(target=feeder, args=(p,), daemon=True)
                   for p in ("interactive", "background")]
        for f in feeders:
            f.start()
        time.sleep(0.2)             # traffic in flight first
        calm = {"queue_wait_p99_ms": 0.0, "util": 0.0,
                "kv_headroom": None}
        auto = FleetAutoscaler(
            fleet, AutoscalerConfig(scale_up_cooldown=0.0,
                                    scale_down_cooldown=0.0,
                                    drain_grace=0.2),
            signals=lambda: {"unified": dict(calm)})
        deadline = time.perf_counter() + 300.0
        while fleet.tier_actual("unified") > 1:   # drain-migrate-kill
            if time.perf_counter() > deadline:
                raise RuntimeError("scale-down drain never completed")
            auto.step()
            time.sleep(0.05)
        fleet.rollout("v2", bake_s=0.5)           # under the same traffic
        stop.set()
        for f in feeders:
            f.join(timeout=300.0)
        assert not failures, \
            f"request lost across scale-down/rollout: {failures[0]!r}"
        c = fleet.snapshot()["counters"]
        assert c.get("migrations_requested", 0) >= 1, c
        client.close()
        return unloaded_p99, loaded_p99, bg_p99, 0
    finally:
        fleet.stop()


def bench_fleet_soak(rows=2, workers=8, slow_delay_s=0.25,
                     n_timed=16, soak_probe_deadline_ms=60.0,
                     seed=20):
    """Seeded chaos soak: a live 3-replica CPU fleet driven through a
    GRAY failure (one replica alive-per-heartbeat but slow on every
    dispatch — chaos ``slow_task``), a SIGKILL + autoscaler-tick
    self-heal, a link sever, and a blue-green rollout, under continuous
    two-class deadline-carrying traffic.  In-bench asserts (the PR's
    acceptance criteria):

    * ``fleet_soak_lost_requests`` == 0 — every feeder request
      completes (failover, migration, and the rollout are lossless);
    * deadline conformance — every deadline-carrying reply (completion
      OR deadline_exceeded error) lands within deadline + epsilon,
      and the short-deadline probes against long decodes come back as
      explicit ``deadline_exceeded`` about at their deadline (the
      in-batcher cancel), never as a late completion;
    * ``fleet_soak_retry_amplification`` <= 1.5 — attempts per
      completed request stay bounded through all of the above (the
      retry budget's job);
    * the slow replica is breaker-isolated (state OPEN, latency
      outlier) while the registry still reports it ALIVE — and the
      CONTROL arm (same seed, same fault, breakers disabled) shows the
      interactive p99 degrading toward the injected delay, proving the
      mechanism and not the workload.
    """
    import threading

    from tfmesos_tpu.backends.local import LocalBackend
    from tfmesos_tpu.chaos import Fault, FaultPlan
    from tfmesos_tpu.fleet.admission import PriorityClass
    from tfmesos_tpu.fleet.autoscaler import (AutoscalerConfig,
                                              FleetAutoscaler)
    from tfmesos_tpu.fleet.client import FleetClient, RequestFailed
    from tfmesos_tpu.fleet.launcher import FleetServer

    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, 97, size=(8,)).astype(np.int32)
               for _ in range(16)]
    classes = [PriorityClass("interactive", weight=8.0, rank=1),
               PriorityClass("background", weight=1.0, rank=0)]
    eps_s = 2.0                     # CPU-scale scheduling epsilon

    p99 = _p99

    def build(breakers):
        plan = FaultPlan([], seed=seed)
        fleet = FleetServer(
            replicas=3, rows=rows, tiny=True, max_len=64, page_size=16,
            prefill_bucket=16, workers=workers, max_queue=256,
            priority_classes=classes, breakers=breakers,
            min_replicas=1, max_replicas=3,
            # Pure tail-based retention: no head sampling, the slow
            # threshold well under the injected delay — the gray
            # failure's traces must retain themselves.
            trace_sample=0.0,
            trace_slow_ms=slow_delay_s * 1000.0 * 0.6,
            request_timeout=300.0, start_timeout=300.0,
            backend=LocalBackend(chaos=plan))
        fleet.start()
        # The gray victim is chosen deterministically; its fault is
        # appended post-start (addresses exist only now) with an
        # explicit delay so the plan stays seed-reproducible.
        victim = sorted(r.addr for r in fleet.registry.alive())[0]
        plan.faults.append(Fault("slow_task", "wire.send", nth=1,
                                 target=victim, delay_s=slow_delay_s))
        plan.install()
        return plan, fleet, victim

    def timed_interactive(client, n):
        walls = []
        for i in range(n):
            t0 = time.perf_counter()
            client.generate(prompts[i % len(prompts)], 2,
                            priority="interactive", timeout=300.0,
                            deadline_ms=120000.0)
            walls.append((time.perf_counter() - t0) * 1000.0)
        return walls

    # ---- main arm: breakers ON, the full chaos timeline ----
    plan, fleet, victim = build(breakers=True)
    lost, completions = [], []
    stop = threading.Event()
    lock = threading.Lock()

    def feeder(priority, new_tokens):
        client = FleetClient(fleet.addr, fleet.token, timeout=300.0)
        i = 0
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                client.generate(prompts[i % len(prompts)], new_tokens,
                                priority=priority, timeout=300.0,
                                deadline_ms=120000.0)
                with lock:
                    completions.append(
                        (time.perf_counter() - t0, 120.0))
            except Exception as e:  # noqa: BLE001 - every loss recorded
                if not stop.is_set():
                    with lock:
                        lost.append(e)
                    return
            i += 1
        client.close()

    try:
        client = FleetClient(fleet.addr, fleet.token, timeout=300.0)
        client.generate(prompts[0], 2)              # warm the compiles
        feeders = [
            threading.Thread(target=feeder, args=("interactive", 2),
                             daemon=True),
            threading.Thread(target=feeder, args=("interactive", 2),
                             daemon=True),
            threading.Thread(target=feeder, args=("background", 8),
                             daemon=True),
        ]
        for f in feeders:
            f.start()

        # Phase A — gray failure: traffic feeds the latency EWMAs until
        # the victim's breaker trips on the outlier, while its
        # heartbeats keep it ALIVE in the registry the whole time.
        deadline = time.perf_counter() + 300.0
        while victim not in fleet.router.breakers.open_addrs():
            assert time.perf_counter() < deadline, \
                "slow replica never breaker-isolated"
            assert not lost, f"request lost in gray phase: {lost[0]!r}"
            time.sleep(0.05)
        assert victim in [r.addr for r in fleet.registry.alive()], \
            "victim must be heartbeat-alive while breaker-open " \
            "(that is what makes the failure gray)"
        on_p99 = p99(timed_interactive(client, n_timed))

        # Deadline probes: long decodes against a deadline far shorter
        # than they need — the reply must be an explicit
        # deadline_exceeded about AT the deadline (in-batcher cancel /
        # router fail-fast), and any completion must beat it.
        probe_violations = 0
        for i in range(4):
            t0 = time.perf_counter()
            try:
                client.generate(prompts[i], 48,
                                deadline_ms=soak_probe_deadline_ms,
                                timeout=300.0)
                wall_s = time.perf_counter() - t0
                if wall_s > soak_probe_deadline_ms / 1000.0 + eps_s:
                    probe_violations += 1    # post-deadline completion
            except RequestFailed as e:
                wall_s = time.perf_counter() - t0
                if e.kind != "deadline_exceeded" \
                        or wall_s > soak_probe_deadline_ms / 1000.0 \
                        + eps_s:
                    probe_violations += 1
        assert probe_violations == 0, \
            f"{probe_violations} deadline probes violated conformance"

        # Phase B — hard churn: SIGKILL a healthy (non-victim) replica
        # whole (process group — a real death, in-flight work fails
        # over); hand-stepped autoscaler ticks relaunch it (crash
        # self-heal).  The death must be OBSERVED (task table or
        # registry) before convergence is waited on, or the wait would
        # trivially pass against the pre-kill state.
        members = {r.addr: r for r in fleet.registry.members()}
        dead_node = next(r.node for a, r in sorted(members.items())
                         if a != victim and r.node)
        assert plan.kill(dead_node), f"no pid for {dead_node}"
        deadline = time.perf_counter() + 300.0
        while fleet.tier_actual("unified") >= 3 \
                and len(fleet.registry.alive()) >= 3:
            assert time.perf_counter() < deadline, \
                "SIGKILLed replica never observed dead"
            time.sleep(0.05)
        calm = {"queue_wait_p99_ms": 0.0, "util": 0.5,
                "kv_headroom": None}
        auto = FleetAutoscaler(
            fleet, AutoscalerConfig(scale_up_cooldown=0.0,
                                    scale_down_cooldown=0.0),
            signals=lambda: {"unified": dict(calm)})
        deadline = time.perf_counter() + 300.0
        while fleet.tier_actual("unified") < 3 \
                or len(fleet.registry.alive()) < 3:
            assert time.perf_counter() < deadline, \
                "autoscaler never relaunched the killed replica"
            auto.step()
            time.sleep(0.1)

        # A one-shot link sever against a healthy replica: the router
        # drops the link, retries elsewhere, the heartbeat revives it.
        other = next(a for a in sorted(
            r.addr for r in fleet.registry.alive()) if a != victim)
        plan.faults.append(Fault("sever", "wire.send", nth=1,
                                 target=other, delay_s=0.0))

        # Phase C — blue-green rollout under the same traffic.
        fleet.rollout("v2", bake_s=0.3)
        stop.set()
        for f in feeders:
            f.join(timeout=300.0)
        assert not lost, f"request lost in soak: {lost[0]!r}"
        # Deadline conformance over the whole soak: no completion came
        # back after its (generous) deadline + epsilon.
        late = [w for w, dl in completions if w > dl + eps_s]
        assert not late, f"{len(late)} completions beat their deadline"

        # Tracing attribution (PR 10 acceptance): the injected
        # slow_task delay is VISIBLE in the slow replica's traced
        # spans — a retained trace holds a router attempt toward the
        # victim carrying (at least) the injected delay, with the
        # chaos firing recorded on the same trace.  The gray failure
        # becomes attributable, not just breaker-detected.
        slow_attempt_ms = 0.0
        traced_fault = False
        for rec in fleet.tracebook.slowest(100):
            spans = rec.get("spans") or ()
            has_fault = any(s.get("component") == "chaos"
                            and s.get("action") == "slow_task"
                            and victim in str(s.get("key", ""))
                            for s in spans)
            for s in spans:
                if s.get("component") == "router" \
                        and s.get("addr") == victim \
                        and s.get("dur", 0.0) >= slow_delay_s * 900.0:
                    slow_attempt_ms = max(slow_attempt_ms,
                                          float(s["dur"]))
                    traced_fault = traced_fault or has_fault
        assert slow_attempt_ms > 0.0, \
            "injected slow_task delay not visible in any traced span " \
            "toward the slow replica"
        assert traced_fault, \
            "chaos slow_task firing not attributed inside the trace"
        traces_detailed = fleet.tracebook.describe()["detailed"]

        c = fleet.snapshot()["counters"]
        completed = c.get("completed", 1)
        amplification = (completed + c.get("retries", 0)) \
            / max(1, completed)
        assert amplification <= 1.5, \
            f"retry amplification {amplification:.3f} > 1.5"
        n_requests = len(completions)
        client.close()
    finally:
        stop.set()
        plan.uninstall()
        fleet.stop()

    # ---- control arm: breakers OFF, same seed, same gray fault ----
    plan, fleet, victim = build(breakers=False)
    try:
        client = FleetClient(fleet.addr, fleet.token, timeout=300.0)
        client.generate(prompts[0], 2)              # warm the compiles
        # Background pressure so p2c spreads the timed requests over
        # the whole tier (idle fleets always pick the least-loaded).
        stop = threading.Event()

        def pressure():
            i = 0
            while not stop.is_set():
                try:
                    client.generate(prompts[i % len(prompts)], 8,
                                    priority="background",
                                    timeout=300.0)
                except Exception:   # noqa: BLE001 - ambient load only
                    return
                i += 1

        bg = threading.Thread(target=pressure, daemon=True)
        bg.start()
        control_walls = timed_interactive(client, 3 * n_timed)
        stop.set()
        bg.join(timeout=300.0)
        control_p99 = p99(control_walls)
        client.close()
    finally:
        stop.set()
        plan.uninstall()
        fleet.stop()
    assert control_p99 > on_p99, \
        (f"control (no breakers) p99 {control_p99:.1f}ms not above "
         f"breakered p99 {on_p99:.1f}ms — isolation unproven")
    assert max(control_walls) >= slow_delay_s * 1000.0, \
        "control arm never even touched the slow replica"
    return (0, amplification, on_p99, control_p99, n_requests,
            slow_attempt_ms, traces_detailed)


def bench_fleet_sim(replicas=1000, n_requests=1_000_000, seed=0):
    """Fleet-simulator scale + fidelity bench (docs/SIMULATOR.md).

    Two in-bench asserts:

    * SCALE — the ``scale`` scenario (the REAL admission/router/
      containment/registry code on the virtual clock, 1000 simulated
      replicas, >= 1M requests, zero lost) completes in under 60s of
      CPU, recording ``sim_events_per_sec`` and
      ``sim_replicas_per_wallclock_sec`` (simulated replica-seconds
      per wall second) so per-request control-plane cost regressions
      surface as a throughput drop.
    * FIDELITY — the ``soak-replay`` scenario replays the seeded
      ``bench_fleet_soak`` chaos timeline and must reproduce its
      qualitative outcomes: the gray-slow replica breaker-isolated
      (latency outlier) while heartbeat-alive, zero lost requests,
      retry amplification <= 1.5, conformant deadline probes.
    """
    from tfmesos_tpu.fleet.sim import run_scenario

    w0 = time.perf_counter()
    c0 = time.process_time()
    out = run_scenario("scale", n_requests=n_requests,
                       replicas=replicas, seed=seed)
    wall_s = time.perf_counter() - w0
    cpu_s = time.process_time() - c0
    assert out["requests"] >= n_requests, out["requests"]
    assert out["lost"] == 0, f"{out['lost']} requests lost in the sim"
    assert min(wall_s, cpu_s) < 60.0, \
        (f"{replicas}-replica / {n_requests}-request scenario took "
         f"{wall_s:.1f}s wall / {cpu_s:.1f}s CPU (budget: 60s)")

    fid = run_scenario("soak-replay", seed=20)
    assert fid["victim_isolated"], "gray replica never breaker-isolated"
    assert fid["victim_alive_while_isolated"], \
        "victim not heartbeat-alive while isolated (not a gray failure)"
    assert fid["victim_trip_reason"] == "latency_outlier", \
        fid["victim_trip_reason"]
    assert fid["lost"] == 0, f"{fid['lost']} requests lost in soak replay"
    assert fid["retry_amplification"] <= 1.5, fid["retry_amplification"]
    assert fid["probes_conformant"], fid["probe_outcomes"]

    # DIURNAL 10x — the ``diurnal`` scenario at 10,000 simulated
    # replicas under a sinusoidal day/night arrival envelope with
    # seeded flash crowds (sharded heartbeats, stretched liveness
    # cadence): the hot path must HOLD the scale scenario's events/s
    # within 2x at 10x the replica count, zero lost.  Recorded as
    # ``sim_events_per_sec_10k`` next to ``sim_events_per_sec``.
    diu = run_scenario("diurnal", n_requests=max(200_000, n_requests // 4),
                       replicas=10 * replicas, seed=seed)
    assert diu["lost"] == 0, f"{diu['lost']} requests lost (diurnal)"
    eps_10k = diu["sim_events_per_sec_10k"]
    assert eps_10k >= 0.5 * out["sim_events_per_sec"], \
        (f"10k-replica diurnal hot path fell below half the "
         f"{replicas}-replica floor: {eps_10k:.0f} vs "
         f"{out['sim_events_per_sec']:.0f} events/s")
    return (out["sim_events_per_sec"],
            out["sim_replicas_per_wallclock_sec"], wall_s,
            out["requests"], out["sim_seconds"],
            fid["retry_amplification"], eps_10k)


def bench_fleet_offline_lane(n_requests=1200, replicas=3, seed=13):
    """The OFFLINE lane (ROADMAP 6b): the ``offline-lane`` scenario's
    lane-on arm vs the lane-off baseline on the same seed — a diurnal
    interactive envelope whose trough leaves slots idle, plus a
    deadline-less batch backlog submitted through the strict-priority
    ``batch`` class.  In-bench asserts: fleet utilization STRICTLY
    higher with the lane on, interactive p99 held within the PR 7
    epsilon convention (1.5x + a small absolute floor), ZERO requests
    lost in either arm, and the whole batch backlog completes."""
    from tfmesos_tpu.fleet.sim import run_sweep

    rows = dict(run_sweep("offline-lane", "batch_lane",
                          ["false", "true"],
                          n_requests=n_requests, replicas=replicas,
                          seed=seed))
    off, on = rows["false"], rows["true"]
    assert on["lost"] == 0 and off["lost"] == 0, \
        f"offline-lane arms lost requests: on={on['lost']} " \
        f"off={off['lost']}"
    assert on["utilization"] > off["utilization"], \
        (f"batch lane did not raise fleet utilization: "
         f"{on['utilization']:.4f} (on) vs {off['utilization']:.4f} "
         f"(off)")
    on_p99 = on["classes"]["interactive"]["p99_ms"]
    off_p99 = off["classes"]["interactive"]["p99_ms"]
    assert on_p99 <= max(1.5 * off_p99, off_p99 + 150.0), \
        (f"interactive p99 not held with the batch lane on: "
         f"{on_p99:.1f}ms vs {off_p99:.1f}ms baseline")
    n_batch = on["batch_planned"]
    assert n_batch > 0 and on["classes"]["batch"]["count"] == n_batch, \
        "the batch backlog did not complete through the lane"
    return (on["utilization"], off["utilization"], on_p99, off_p99,
            on.get("batch_deferrals", 0), n_batch)


def bench_http_keepalive(n_requests=200):
    """HTTP ingress connection reuse, before/after: requests/s for
    ``n_requests`` sequential POST /v1/completions over ONE kept-alive
    connection vs a fresh connection per request (the pre-keep-alive
    behavior — every request paid connect + teardown).  Echo gateway,
    no fleet, no jax: the delta is pure connection-lifecycle cost."""
    import json as json_mod
    import socket as socket_mod
    import threading

    from tfmesos_tpu import wire
    from tfmesos_tpu.fleet.http import HttpIngress

    class _Echo:
        def handle_ingress(self, reply, msg):
            toks = list(msg.get("prompt", []))
            threading.Thread(
                target=lambda: reply.send(
                    {"op": "completion", "id": msg.get("id"),
                     "tokens": toks, "ttft_ms": 1.0, "total_ms": 2.0}),
                daemon=True).start()

    body = json_mod.dumps({"prompt": [1, 2, 3],
                           "max_tokens": 4}).encode()
    raw = (b"POST /v1/completions HTTP/1.1\r\n"
           b"Content-Type: application/json\r\n"
           + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)

    def read_response(s, buf):
        while b"\r\n\r\n" not in buf:
            buf += s.recv(65536)
        head, _, rest = buf.partition(b"\r\n\r\n")
        clen = 0
        for line in head.split(b"\r\n")[1:]:
            k, _, v = line.partition(b":")
            if k.strip().lower() == b"content-length":
                clen = int(v.strip())
        while len(rest) < clen:
            rest += s.recv(65536)
        return rest[clen:]

    srv = wire.WireServer(lambda conn, msg: None, token="bench",
                          name="http-bench")
    srv.add_ingress(HttpIngress(_Echo()))
    srv.start()
    try:
        host, _, port = srv.ingress_addrs[0].rpartition(":")
        addr = (host, int(port))
        # AFTER: one connection, n_requests ride it back to back.
        with socket_mod.create_connection(addr, timeout=30.0) as s:
            s.settimeout(30.0)
            buf = b""
            read_response(s, s.sendall(raw) or buf)   # warm
            t0 = time.perf_counter()
            buf = b""
            for _ in range(n_requests):
                s.sendall(raw)
                buf = read_response(s, buf)
            keep_rps = n_requests / (time.perf_counter() - t0)
        # BEFORE: a fresh connection (connect + close) per request.
        t0 = time.perf_counter()
        for _ in range(n_requests):
            with socket_mod.create_connection(addr, timeout=30.0) as s:
                s.settimeout(30.0)
                s.sendall(raw)
                read_response(s, b"")
        close_rps = n_requests / (time.perf_counter() - t0)
    finally:
        srv.stop()
    return keep_rps, close_rps


def _gateway_flood(addr, token, n_conns, prompt, max_new_tokens=4,
                   timeout_s=180.0):
    """Selector-driven N-connection client harness: open ``n_conns``
    sockets to one gateway, send one STREAMED generate on each, and
    drive every reply with ONE loop (the client-side mirror of the
    event-loop server — a thread per connection on the client would
    measure client thread scheduling, not the front door).  Returns
    ``(ttfts_ms, completed, failed)`` where TTFT is send-to-first-
    token-frame per connection, measured while ALL connections are in
    flight."""
    import selectors
    import socket as socket_mod

    from tfmesos_tpu import wire

    class _Conn:
        __slots__ = ("sock", "framer", "t0", "ttft_ms", "done", "ok")

    sel = selectors.DefaultSelector()
    host, port = addr.rsplit(":", 1)
    conns = []
    for i in range(n_conns):
        s = socket_mod.create_connection((host, int(port)), timeout=30.0)
        s.setsockopt(socket_mod.IPPROTO_TCP, socket_mod.TCP_NODELAY, 1)
        st = _Conn()
        st.sock, st.framer = s, wire.Framer(token)
        st.t0 = st.ttft_ms = None
        st.done = st.ok = False
        conns.append(st)
    # Every link is OPEN before the first request goes out: the claim
    # is concurrent connections, not sequential reuse.
    for i, st in enumerate(conns):
        frame = wire.encode(
            {"op": "generate", "id": i, "prompt": prompt,
             "max_new_tokens": max_new_tokens, "stream": True}, token)
        st.sock.sendall(frame)
        st.t0 = time.perf_counter()
        st.sock.setblocking(False)
        sel.register(st.sock, selectors.EVENT_READ, st)
    remaining = n_conns
    deadline = time.monotonic() + timeout_s

    def finish(st, ok):
        nonlocal remaining
        if st.done:
            return
        st.done, st.ok = True, ok
        remaining -= 1
        try:
            sel.unregister(st.sock)
        except (KeyError, ValueError):
            pass
        try:
            st.sock.close()
        except OSError:
            pass

    while remaining and time.monotonic() < deadline:
        for key, _ in sel.select(timeout=1.0):
            st = key.data
            try:
                data = st.sock.recv(65536)
            except BlockingIOError:
                continue
            except OSError:
                data = b""
            if not data:
                finish(st, False)
                continue
            try:
                msgs = st.framer.feed(data)
            except wire.WireError:
                finish(st, False)
                continue
            for msg in msgs:
                op = msg.get("op") if isinstance(msg, dict) else None
                if st.ttft_ms is None and op in ("tokens", "completion",
                                                 "error"):
                    st.ttft_ms = (time.perf_counter() - st.t0) * 1000.0
                if op in ("completion", "error"):
                    finish(st, op == "completion")
                    break
    for st in conns:
        if not st.done:
            finish(st, False)
    sel.close()
    ttfts = [st.ttft_ms for st in conns if st.ttft_ms is not None]
    completed = sum(1 for st in conns if st.ok)
    return ttfts, completed, n_conns - completed


def bench_fleet_gateway_concurrency(n_conns=1100, kill_threads=8,
                                    kill_requests=30, workers=32,
                                    seed=11):
    """Front-door scale bench (ROADMAP item 2 acceptance;
    docs/SERVING.md "Front-door scaling").  jax-free — the event-loop
    gateway/registry/router/mux machinery IS the system under test;
    replicas are stub handlers replying streamed canned tokens.

    Two phases, both asserted in-bench:

    * CONCURRENCY — ``n_conns`` (>= 1000) simultaneous client
      connections against ONE gateway (one selector thread server-side)
      each issue a streamed generate; every one must complete and the
      p99 send-to-first-token TTFT must stay bounded (< 10s) with all
      links in flight — the thread-per-connection front door could not
      hold 1000 links at all.  Records
      ``fleet_gateway_concurrent_connections`` (= connections that
      completed) and ``fleet_gateway_flood_p99_ttft_ms``.
    * KILL SOAK — continuous traffic from ``kill_threads`` clients
      across TWO gateways sharing the one registry/router view; one
      gateway is hard-killed mid-traffic (sockets slam shut, no
      deregistration — the SIGKILL shape).  Clients fail over and
      REPLAY idempotent in-flight requests on the survivor: zero lost
      requests asserted, and the post-kill p99 TTFT must hold within
      2x of the pre-kill p99 (+500ms CPU-scheduler epsilon).  Records
      ``fleet_gateway_prekill_p99_ttft_ms`` /
      ``fleet_gateway_kill_p99_ttft_ms`` /
      ``fleet_gateway_lost_requests``.
    """
    import threading

    from tfmesos_tpu import wire
    from tfmesos_tpu.fleet.admission import AdmissionController
    from tfmesos_tpu.fleet.client import FleetClient
    from tfmesos_tpu.fleet.gateway import Gateway
    from tfmesos_tpu.fleet.metrics import FleetMetrics
    from tfmesos_tpu.fleet.registry import ReplicaRegistry
    from tfmesos_tpu.fleet.replica import ReplicaServer
    from tfmesos_tpu.fleet.router import Router

    try:                            # headroom for ~2x n_conns fds
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        want = 4 * n_conns + 512
        if soft < want:
            resource.setrlimit(resource.RLIMIT_NOFILE,
                               (min(want, hard), hard))
            soft = min(want, hard)
        n_conns = min(n_conns, max(64, (soft - 512) // 4))
    except (ImportError, ValueError, OSError):
        pass

    token = wire.new_token()
    reg = ReplicaRegistry(token=token, suspect_after=2.0, dead_after=5.0,
                          evict_after=20.0, sweep_interval=0.2).start()

    def stub(tokens):
        # Synchronous streamed replies (no thread per request): a
        # `tokens` partial first — the TTFT marker — then the final
        # completion.  The front door, not replica compute, is what
        # this bench loads.
        def handler(msg, reply):
            mid = msg.get("id")
            if msg.get("stream"):
                reply.partial({"op": "tokens", "id": mid, "off": 0,
                               "tokens": list(tokens)})
            reply({"op": "completion", "id": mid,
                   "tokens": list(tokens), "ttft_ms": 1.0,
                   "total_ms": 2.0})

        return ReplicaServer(handler, token=token, capacity=4096,
                             registry_addr=reg.addr,
                             heartbeat_interval=0.2).start()

    reps = [stub((7, 3)) for _ in range(3)]
    assert reg.wait_for(3, timeout=10.0)
    metrics = FleetMetrics()
    router = Router(reg, metrics, token=token, request_timeout=120.0)
    admission = AdmissionController(max_queue=max(4096, 2 * n_conns))
    gws = [Gateway(router, admission, metrics, token=token,
                   workers=workers, registry=reg,
                   close_router=False).start() for _ in range(2)]
    rng = np.random.default_rng(seed)
    prompt = [int(t) for t in rng.integers(0, 97, size=(8,))]
    p99 = _p99
    try:
        # ---- phase 1: the connection flood against ONE gateway ----
        ttfts, completed, failed = _gateway_flood(
            gws[0].addr, token, n_conns, prompt)
        flood_p99 = p99(ttfts) if ttfts else float("inf")
        assert completed == n_conns, \
            (f"only {completed}/{n_conns} concurrent connections "
             f"served ({failed} failed)")
        assert flood_p99 < 10_000.0, \
            (f"p99 TTFT {flood_p99:.0f}ms unbounded at {n_conns} "
             f"concurrent connections")

        # ---- phase 2: SIGKILL one of two gateways mid-traffic ----
        addrs = [g.addr for g in gws]
        kill_at = [None]
        lost = [0]
        pre_walls, post_walls = [], []
        wlock = threading.Lock()
        start_evt = threading.Event()

        end_at = [None]

        def client_body(k):
            # Alternate initial gateway per client so both front doors
            # carry traffic when the kill lands.
            order = addrs if k % 2 == 0 else addrs[::-1]
            client = FleetClient(order, token, timeout=60.0)
            try:
                start_evt.wait(10.0)
                done = 0
                while done < kill_requests * 20:
                    with wlock:
                        if end_at[0] is not None \
                                and time.perf_counter() >= end_at[0]:
                            break
                    done += 1
                    first = [None]
                    t0 = time.perf_counter()
                    try:
                        client.generate(
                            prompt, 4, timeout=60.0,
                            on_tokens=lambda t: first.__setitem__(
                                0, first[0] or time.perf_counter()))
                    except Exception:
                        with wlock:
                            lost[0] += 1
                        continue
                    tf = first[0] or time.perf_counter()
                    wall = (tf - t0) * 1000.0
                    with wlock:
                        ka = kill_at[0]
                        if ka is None or tf < ka:
                            pre_walls.append(wall)      # finished pre-kill
                        elif t0 >= ka:
                            post_walls.append(wall)     # started post-kill
                        # requests SPANNING the kill (in flight when the
                        # gateway died — the failover-replayed ones) are
                        # counted for losslessness but excluded from both
                        # steady-state percentiles.
            finally:
                client.close()

        threads = [threading.Thread(target=client_body, args=(k,),
                                    daemon=True)
                   for k in range(kill_threads)]
        for t in threads:
            t.start()
        start_evt.set()
        # Let pre-kill traffic accumulate, then slam gateway 0 shut,
        # then keep the traffic running for the post-kill window.
        time.sleep(1.2)
        with wlock:
            kill_at[0] = time.perf_counter()
        gws[0].kill()
        with wlock:
            end_at[0] = time.perf_counter() + 1.5
        for t in threads:
            t.join(timeout=120.0)
        assert lost[0] == 0, \
            f"{lost[0]} idempotent requests lost across the gateway kill"
        assert pre_walls and post_walls, \
            (f"kill landed outside the traffic window "
             f"({len(pre_walls)} pre / {len(post_walls)} post)")
        pre_p99, post_p99 = p99(pre_walls), p99(post_walls)
        assert post_p99 <= max(2.0 * pre_p99, pre_p99 + 500.0), \
            (f"p99 TTFT did not hold across the gateway kill: "
             f"{post_p99:.0f}ms post vs {pre_p99:.0f}ms pre")
        return (completed, flood_p99, pre_p99, post_p99, lost[0])
    finally:
        for g in gws:
            if not g.killed:
                g.stop()
        router.close()
        for r in reps:
            r.stop()
        reg.stop()


def bench_fleet_gateway_procs(n_procs=4, threads=12, window_s=2.0,
                              workers=16, seed=13):
    """Multi-process front door bench (docs/SERVING.md "Multi-process
    gateways").  jax-free — REAL gateway OS processes (``python -m
    tfmesos_tpu.fleet.gateway``, the ``tfserve --gateway-processes N``
    unit) routed over stub replicas; one CPython event loop per
    process, so N processes are the only way past one GIL.

    Phases, all asserted in-bench:

    * SATURATION — a closed-loop flood from ``threads`` wire clients
      for ``window_s`` against ONE gateway process, then against
      ``n_procs`` processes sharing ONE public port via SO_REUSEPORT
      (per-process ports behind the registry's discovery op where
      REUSEPORT is unavailable): with >1 CPU core the N-process
      completed-requests/s must STRICTLY beat the single process
      (``fleet_gateway_procs_rps_n`` vs ``fleet_gateway_procs_rps_1``);
      on a single core N processes cannot beat one by physics (there
      is no second core to run on), so the assert becomes a bounded
      oversubscription cost (>= 0.25x) and the recorded mode says so.
    * KILL SOAK — mid-window in the N-process run, one process is
      SIGKILLED.  Clients reconnect (the kernel steers new
      connections to surviving REUSEPORT listeners) and REPLAY
      idempotent in-flight requests — the PR 12 failover contract,
      verbatim, across an OS-process death: zero lost asserted,
      post-kill p99 TTFT recorded next to pre-kill.
    """
    import os
    import signal
    import subprocess
    import sys
    import threading

    from tfmesos_tpu import wire
    from tfmesos_tpu.fleet.client import FleetClient
    from tfmesos_tpu.fleet.registry import ReplicaRegistry
    from tfmesos_tpu.fleet.replica import ReplicaServer

    token = wire.new_token()
    reg = ReplicaRegistry(token=token, suspect_after=2.0, dead_after=5.0,
                          evict_after=20.0, sweep_interval=0.2).start()

    def stub():
        def handler(msg, reply):
            mid = msg.get("id")
            if msg.get("stream"):
                reply.partial({"op": "tokens", "id": mid, "off": 0,
                               "tokens": [7, 3]})
            reply({"op": "completion", "id": mid, "tokens": [7, 3],
                   "ttft_ms": 1.0, "total_ms": 2.0})

        return ReplicaServer(handler, token=token, capacity=4096,
                             registry_addr=reg.addr,
                             heartbeat_interval=0.2).start()

    reps = [stub() for _ in range(3)]
    assert reg.wait_for(3, timeout=10.0)
    env = dict(os.environ, TPUMESOS_TOKEN=token)
    env.pop("TPUMESOS_TOKEN_FILE", None)
    reuseport = wire.reuseport_available()
    procs = []
    rng = np.random.default_rng(seed)
    prompt = [int(t) for t in rng.integers(0, 97, size=(8,))]
    p99 = _p99

    def spawn(port, reuse):
        cmd = [sys.executable, "-m", "tfmesos_tpu.fleet.gateway",
               "--registry", reg.addr, "--host", "127.0.0.1",
               "--port", str(port), "--workers", str(workers)]
        if reuse:
            cmd.append("--reuseport")
        p = subprocess.Popen(cmd, env=env, stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
        procs.append(p)
        return p

    def wait_gateways(n, timeout=30.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(reg.gateway_leases()) >= n:
                return
            for p in procs:
                if p.poll() is not None:
                    raise AssertionError(
                        f"gateway process exited rc={p.returncode} "
                        f"during bring-up")
            time.sleep(0.05)
        raise AssertionError(
            f"only {len(reg.gateway_leases())}/{n} gateway "
            f"process(es) registered within {timeout:.0f}s")

    def wait_mirrors(want, timeout=15.0):
        # Each process's sidecar mirror must route to every alive stub
        # before traffic starts (the launcher's bring-up barrier).
        pending = set(reg.gateway_leases())
        deadline = time.monotonic() + timeout
        while pending and time.monotonic() < deadline:
            for addr in sorted(pending):
                try:
                    sock = wire.connect(addr, timeout=2.0)
                    try:
                        sock.settimeout(2.0)
                        wire.send_msg(sock, {"op": "status"}, token)
                        reply = wire.recv_msg(sock, token)
                    finally:
                        sock.close()
                except (OSError, wire.WireError):
                    continue
                alive = reply.get("alive") if isinstance(reply, dict) \
                    else None
                if isinstance(alive, int) and alive >= want:
                    pending.discard(addr)
            if pending:
                time.sleep(0.05)
        assert not pending, \
            f"{len(pending)} gateway mirror(s) never converged"

    def reap():
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=5.0)
        procs.clear()
        for a in list(reg.gateway_addrs()):
            reg.unregister_gateway(a)

    def pump(addrs, window, kill_proc=None):
        """Closed-loop flood: ``threads`` clients, each measuring
        send-to-first-token per request.  Returns (rps, ttft recs,
        lost, kill timestamp)."""
        recs = []                   # (t0, t_first, wall_ms)
        lost = [0]
        kill_at = [None]
        tl = threading.Lock()
        start_evt = threading.Event()
        end_at = [None]

        def body(k):
            rot = k % len(addrs)
            order = addrs[rot:] + addrs[:rot]
            if len(order) == 1:
                # One shared REUSEPORT addr: failover = reconnect to
                # the same public door (the kernel re-picks a live
                # listener process).
                order = order * 2
            client = FleetClient(order, token, timeout=30.0)
            try:
                start_evt.wait(10.0)
                while time.perf_counter() < end_at[0]:
                    first = [None]
                    t0 = time.perf_counter()
                    try:
                        client.generate(
                            prompt, 4, timeout=30.0,
                            on_tokens=lambda t: first.__setitem__(
                                0, first[0] or time.perf_counter()))
                    except Exception:
                        with tl:
                            lost[0] += 1
                        continue
                    tf = first[0] or time.perf_counter()
                    with tl:
                        recs.append((t0, tf, (tf - t0) * 1000.0))
            finally:
                client.close()

        tls = [threading.Thread(target=body, args=(k,), daemon=True)
               for k in range(threads)]
        for t in tls:
            t.start()
        end_at[0] = time.perf_counter() + window
        start_evt.set()
        if kill_proc is not None:
            time.sleep(window / 2.0)
            with tl:
                kill_at[0] = time.perf_counter()
            os.kill(kill_proc.pid, signal.SIGKILL)
        for t in tls:
            t.join(timeout=60.0)
        rps = len(recs) / window
        return rps, recs, lost[0], kill_at[0]

    try:
        # ---- phase 1: one gateway process ----
        spawn(0, False)
        wait_gateways(1)
        wait_mirrors(3)
        addrs1 = sorted(reg.gateway_addrs())
        rps_1, recs_1, lost_1, _ = pump(addrs1, window_s)
        assert lost_1 == 0, f"{lost_1} requests lost against 1 process"
        assert recs_1, "no requests completed against 1 process"
        p99_1 = p99([r[2] for r in recs_1])
        reap()

        # ---- phase 2: N processes + mid-window SIGKILL ----
        if reuseport:
            probe = wire.bind_ephemeral("127.0.0.1", 0, reuseport=True)
            shared_port = probe.getsockname()[1]
            probe.close()
            for _ in range(n_procs):
                spawn(shared_port, True)
        else:
            for _ in range(n_procs):
                spawn(0, False)
        wait_gateways(n_procs)
        wait_mirrors(3)
        addrs_n = sorted(reg.gateway_addrs())
        if reuseport:
            assert len(addrs_n) == 1, addrs_n   # ONE public door
        # 2a: clean saturation window (no kill) — the rps comparison.
        rps_n, recs_n, lost_n, _ = pump(addrs_n, window_s)
        assert lost_n == 0, \
            f"{lost_n} requests lost against {n_procs} processes"
        cores = os.cpu_count() or 1
        if cores > 1:
            assert rps_n > rps_1, \
                (f"{n_procs} gateway processes did not beat 1 on "
                 f"{cores} cores: {rps_n:.0f} vs {rps_1:.0f} rps")
        else:
            # One core: no parallel win is possible — the contract
            # shrinks to bounded oversubscription cost.
            assert rps_n >= 0.25 * rps_1, \
                (f"{n_procs} gateway processes collapsed on one core: "
                 f"{rps_n:.0f} vs {rps_1:.0f} rps")
        # 2b: kill soak — SIGKILL one process mid-window; clients
        # replay in-flight idempotent requests on reconnect.
        rps_k, recs_k, lost_k, ka = pump(
            addrs_n, window_s, kill_proc=procs[-1])
        assert lost_k == 0, \
            (f"{lost_k} idempotent requests lost across the "
             f"gateway-process SIGKILL")
        pre = [r[2] for r in recs_k if r[1] < ka]
        post = [r[2] for r in recs_k if r[0] >= ka]
        assert pre and post, \
            (f"kill landed outside the traffic window "
             f"({len(pre)} pre / {len(post)} post)")
        mode = ("reuseport" if reuseport else "discovery") \
            + ("-1core" if cores == 1 else "")
        return (rps_1, rps_n, p99_1, p99(pre), p99(post),
                lost_k, mode)
    finally:
        reap()
        for r in reps:
            r.stop()
        reg.stop()


def bench_fleet_trace_overhead(n_requests=240, workers=4, threads=2,
                               handler_delay_s=0.01, best_of=3):
    """Tracing overhead bound (PR 10 acceptance): the same seeded stub
    workload — jax-free; the gateway/router/tracing machinery IS the
    system under test — run with tracing at summary-only vs FULL span
    detail on every request; the detailed arm's p99 must land within
    5% of summary-only (+1ms absolute epsilon absorbing CPU scheduler
    noise at these few-ms latencies).  Arms alternate order and each
    takes its best-of-``best_of`` p99 — at this scale the scheduler's
    tail jitter is bigger than any real software cost, and only the
    min is a stable estimator of it.  Records
    ``fleet_trace_overhead_pct``."""
    import threading as _threading

    from tfmesos_tpu import wire as _wire
    from tfmesos_tpu.fleet.admission import AdmissionController
    from tfmesos_tpu.fleet.client import FleetClient
    from tfmesos_tpu.fleet.gateway import Gateway
    from tfmesos_tpu.fleet.metrics import FleetMetrics
    from tfmesos_tpu.fleet.registry import ReplicaRegistry
    from tfmesos_tpu.fleet.replica import ReplicaServer
    from tfmesos_tpu.fleet.router import Router
    from tfmesos_tpu.fleet.tracing import TraceBook

    p99 = _p99

    def arm(sample, detail):
        token = _wire.new_token()
        reg = ReplicaRegistry(token=token, suspect_after=1.0,
                              dead_after=2.0, evict_after=10.0).start()
        servers = []

        def handler(msg, reply):
            def work():
                time.sleep(handler_delay_s)
                reply({"op": "completion", "id": msg.get("id"),
                       "tokens": [1, 2], "ttft_ms": 1.0,
                       "total_ms": 2.0})

            _threading.Thread(target=work, daemon=True).start()

        for _ in range(2):
            servers.append(ReplicaServer(
                handler, token=token, capacity=64,
                registry_addr=reg.addr,
                heartbeat_interval=0.1).start())
        deadline = time.perf_counter() + 30.0
        while len(reg.alive()) < 2 and time.perf_counter() < deadline:
            time.sleep(0.02)
        metrics = FleetMetrics()
        router = Router(reg, metrics, token=token)
        book = TraceBook(sample=sample, slow_ms=1e9)
        gw = Gateway(router, AdmissionController(max_queue=1024),
                     metrics, token=token, workers=workers,
                     tracebook=book).start()
        walls = []
        lock = _threading.Lock()

        def feeder():
            client = FleetClient(gw.addr, token, timeout=60.0)
            for _ in range(n_requests // threads):
                t0 = time.perf_counter()
                client.generate([1, 2, 3, 4], 2,
                                trace=(True if detail else None),
                                timeout=60.0)
                dt = (time.perf_counter() - t0) * 1000.0
                with lock:
                    walls.append(dt)
            client.close()

        try:
            ts = [_threading.Thread(target=feeder)
                  for _ in range(threads)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=120.0)
            assert len(walls) == (n_requests // threads) * threads
            if detail:
                # The detailed arm must actually have traced in detail.
                assert book.describe()["detailed"] == len(walls)
        finally:
            gw.stop()
            for s in servers:
                s.stop()
            reg.stop()
        return p99(walls)

    # Best-of-N per arm, orders alternating so host drift (page cache,
    # cpu governor, background load) cannot masquerade as tracing
    # cost: at few-ms stub latencies one unlucky scheduler stall is
    # bigger than the entire software path under test.
    summaries, details = [], []
    for i in range(best_of):
        if i % 2 == 0:
            summaries.append(arm(0.0, False))
            details.append(arm(1.0, True))
        else:
            details.append(arm(1.0, True))
            summaries.append(arm(0.0, False))
    p99_summary = min(summaries)
    p99_detail = min(details)
    overhead_pct = (p99_detail - p99_summary) / p99_summary * 100.0
    assert p99_detail <= p99_summary * 1.05 + 1.0, \
        (f"tracing overhead unbounded: detailed p99 {p99_detail:.2f}ms "
         f"vs summary-only p99 {p99_summary:.2f}ms "
         f"({overhead_pct:+.1f}%)")
    return overhead_pct, p99_summary, p99_detail


def bench_bandwidth(sizes=None):
    """Achieved bandwidth vs roofline.

    Multi-device: psum sweep (1MB-256MB fp32), algorithmic bytes/s =
    2·(n−1)/n · size / time per all-reduce — the ICI utilization metric
    BASELINE.md promises.  Single chip: there is no ICI, so report an HBM
    triad (c = a + b: 3 moved bytes/element) against the HBM roofline.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    kind = _device_kind()
    n = jax.device_count()
    if sizes is None:
        sizes = [1 << 20, 1 << 23, 1 << 26, 1 << 28]  # bytes: 1MB..256MB
    out = {"allreduce_gbps": None, "hbm_gbps": None,
           "ici_roofline_gbps": ICI_GBPS.get(kind),
           "hbm_roofline_gbps": HBM_GBPS.get(kind)}

    if n > 1:
        mesh = Mesh(np.array(jax.devices()), ("x",))
        best_gbps = {}
        for size in sizes:
            # `size` is the PER-RANK psum payload (the standard algorithmic
            # bandwidth convention): each of the n rows lives on one device.
            elems = size // 4
            x = jnp.ones((n, elems), jnp.float32)
            x = jax.device_put(x, NamedSharding(mesh, P("x")))
            reps = 10

            @jax.jit
            def sweep(x):
                def body(x, _):
                    s = jax.shard_map(
                        lambda v: lax.psum(v, "x"), mesh=mesh,
                        in_specs=P("x"), out_specs=P("x"))(x)
                    return s / n, None  # keep magnitude stable, chain deps
                return lax.scan(body, x, None, length=reps)[0]

            y = sweep(x)
            jax.block_until_ready(y)
            t0 = time.perf_counter()
            y = sweep(x)
            float(np.asarray(y[0, 0]))
            dt = (time.perf_counter() - t0) / reps
            algbw = 2 * (n - 1) / n * size / dt
            best_gbps[size] = algbw / 1e9
        out["allreduce_gbps"] = round(max(best_gbps.values()), 2)
        label = lambda s: f"{s >> 20}MB" if s >= 1 << 20 else f"{s >> 10}KB"
        out["allreduce_sweep"] = {label(s): round(g, 2)
                                  for s, g in best_gbps.items()}
    else:
        # One visible device: there is no inter-chip link to all-reduce
        # over — say WHY the field is absent instead of a bare null
        # (round 5 recorded allreduce_gbps: null with no explanation).
        out["allreduce_skip_reason"] = (
            f"single visible device ({kind or 'unknown kind'}): no ICI "
            f"to measure; hbm_gbps triad recorded instead")
        size = max(sizes)  # largest requested payload (default 256MB)
        elems = size // 4
        a = jnp.ones((elems,), jnp.float32)
        b = jnp.full((elems,), 2.0, jnp.float32)
        reps = 20

        @jax.jit
        def triad(a, b):
            def body(a, _):
                return a * 0.5 + b, None
            return lax.scan(body, a, None, length=reps)[0]

        y = triad(a, b)
        jax.block_until_ready(y)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            y = triad(a, b)
            float(np.asarray(y[0]))
            best = min(best, (time.perf_counter() - t0) / reps)
        out["hbm_gbps"] = round(3 * size / best / 1e9, 1)
    return out


def _probe_device_once(timeout_s: float) -> Optional[str]:
    """Confirm the accelerator answers before committing to the benches.

    A wedged remote-attach relay HANGS jax backend init rather than
    erroring (a killed client's claim can stay held upstream); probing in
    a throwaway subprocess with a deadline turns an all-day hang into a
    parseable failure line the driver can record."""
    import os
    import signal
    import subprocess
    import sys

    # The site PJRT plugin pins the platform via jax.config at interpreter
    # start, so the JAX_PLATFORMS env var alone loses; re-assert it through
    # the config so a deliberately CPU-forced bench run probes CPU.
    code = ("import os, jax, numpy as np\n"
            "p = os.environ.get('JAX_PLATFORMS')\n"
            "if p: jax.config.update('jax_platforms', p)\n"
            "x = jax.numpy.ones((64, 64))\n"
            "print(float(np.asarray((x @ x).sum())))")
    # Own session + killpg on timeout: the child's backend init may spawn
    # helpers that inherit the pipes, and killing only the direct child
    # would leave communicate() blocked on the helpers' open write ends —
    # the exact hang this probe exists to prevent.
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            start_new_session=True)
    try:
        _, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        return f"device probe hung for {timeout_s:.0f}s (relay wedged?)"
    if proc.returncode != 0:
        tail = stderr.decode(errors="replace").strip().splitlines()
        return f"device probe failed rc={proc.returncode}: " + \
            (tail[-1] if tail else "")
    return None


def _probe_device(attempt_timeout_s: float, attempts: int = 1,
                  retry_sleep_s: float = 30.0) -> Optional[str]:
    """Optionally-retrying probe.  Default is ONE attempt: round 4
    spent 3x120s on retries against a relay wedge that never cleared,
    so the default now fails over to CPU after the first hang and
    ``TPUMESOS_PROBE_RETRIES`` opts back into spreading shorter
    attempts over the budget (useful where upstream claim leases are
    known to expire, as round 2's did)."""
    import sys
    import time as _time

    err = None
    for i in range(max(1, attempts)):
        if i:
            _time.sleep(retry_sleep_s)
        err = _probe_device_once(attempt_timeout_s)
        if err is None:
            return None
        print(f"device probe attempt {i + 1}/{attempts}: {err}",
              file=sys.stderr, flush=True)
    return err


def main():
    import os
    import sys
    import traceback

    err = _probe_device(
        float(os.environ.get(
            "TPUMESOS_PROBE_TIMEOUT_S",
            os.environ.get("TPUMESOS_BENCH_PROBE_TIMEOUT", "120"))),
        attempts=int(os.environ.get(
            "TPUMESOS_PROBE_RETRIES",
            os.environ.get("TPUMESOS_BENCH_PROBE_ATTEMPTS", "1"))))
    degraded = None
    if err is not None:
        # The accelerator is unreachable (round 2 lost its whole benchmark
        # to exactly this).  Fall back to CPU so the round still records a
        # real measured number — marked degraded, never value:null.
        degraded = err
        os.environ["JAX_PLATFORMS"] = "cpu"
        cpu_err = _probe_device(60.0, attempts=1)
        if cpu_err is not None:  # something deeper than the relay is broken
            print(json.dumps({
                "metric": "mnist_replica_steps_per_sec_per_chip",
                "value": None, "unit": "steps/s/chip", "vs_baseline": None,
                "error": f"{err}; cpu fallback also failed: {cpu_err}"}),
                flush=True)
            raise SystemExit(err)

    import jax

    if degraded is not None:
        # The site PJRT plugin pins the platform at interpreter start;
        # re-assert CPU through the config so the env var actually wins.
        jax.config.update("jax_platforms", "cpu")

    # Best-of-N: the remote-attach relay adds ±40% latency jitter between
    # runs; the max is the least-interference estimate of chip capability.
    # Individual runs may die on relay hiccups — keep whatever succeeded,
    # with full tracebacks on stderr so deterministic bugs stay debuggable.
    def attempts(fn, label, n=3):
        results = []
        for _ in range(n):
            try:
                results.append(fn())
            except Exception:
                print(f"{label} run failed:", file=sys.stderr)
                traceback.print_exc(file=sys.stderr)
        return results

    # Best-of-8 on the headline: it is cheap (one compile, ~1s/run) and the
    # relay jitter on this metric swamps everything else — round 5 measured
    # 0.753x and 0.997x vs baseline on IDENTICAL code two hours apart, so
    # more draws are the only defense.
    runs = attempts(lambda: bench_mnist_replica(steps=800), "bench", n=8)
    if not runs:
        raise SystemExit("all benchmark runs failed")
    value, final_loss, mlp_mfu = max(runs)
    peak, kind = _peak_flops()
    out = {
        "metric": "mnist_replica_steps_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "steps/s/chip",
        "vs_baseline": round(value / BASELINE_SELF, 3),
        "backend": jax.default_backend(),
        "n_chips": jax.device_count(),
        "device_kind": kind,
        "peak_bf16_tflops": round(peak / 1e12, 1),
        "final_loss": round(final_loss, 4),
        "mfu_mlp": round(mlp_mfu, 5),
    }
    if degraded is not None:
        # CPU stand-in numbers: real, but not comparable to the TPU
        # baseline — say so, null the TPU-relative ratio, and skip the
        # accelerator-scale probes (a T=2048 transformer step on CPU
        # would take minutes each).  Static schedule analytics need no
        # hardware, so the degraded record still carries them.
        out["degraded"] = f"cpu fallback: {degraded}"
        out["vs_baseline"] = None
        del out["peak_bf16_tflops"], out["mfu_mlp"]
        pb = attempts(pipeline_bubble_stats, "pipeline schedule stats",
                      n=1)
        if pb:
            out.update(pb[0])
        print(json.dumps(out), flush=True)
        return
    # The headline metric is in hand; the remaining probes each pay a heavy
    # XLA compile.  Flush a parseable partial line after EVERY section so a
    # relay wedge mid-suite keeps whatever hardware data had landed (round 3
    # protected only the headline) — the final full line supersedes them all.
    def flush_partial():
        print(json.dumps(dict(out, partial=True)), flush=True)

    flush_partial()

    # One attempt each: compile dominates wall-clock for these, and each
    # attempt already takes best-of-`iters` timings internally.
    tr = attempts(bench_transformer_tokens, "transformer bench", n=1)
    if tr:
        toks, mfu = max(tr)
        out["transformer_tokens_per_sec"] = round(toks, 1)
        out["mfu_transformer"] = round(mfu, 4)
        flush_partial()
    dense = attempts(bench_transformer_dense, "dense-mfu bench", n=1)
    if dense:
        _, mfu = max(dense)
        out["mfu_dense"] = round(mfu, 4)
        flush_partial()
    dec = attempts(bench_decode, "decode bench", n=1)
    if dec:
        out["decode_tokens_per_sec"] = round(max(dec), 1)
        flush_partial()
    lat = attempts(lambda: bench_decode(batch=1), "decode latency bench",
                   n=1)
    if lat:
        # Single-stream serving latency: ms per generated token at B=1.
        out["decode_latency_ms_per_token"] = round(1000.0 / max(lat), 3)
        flush_partial()
    dec8 = attempts(lambda: bench_decode(quantized=True),
                    "int8 decode bench", n=1)
    if dec8:
        out["decode_int8_tokens_per_sec"] = round(max(dec8), 1)
        flush_partial()
    dec8kv = attempts(
        lambda: bench_decode(quantized=True, quantized_cache=True,
                             prompt_len=1024, new_tokens=128),
        "int8+int8kv decode bench", n=1)
    if dec8kv:
        # Long-prompt config: at 1k+ cached positions the cache bytes rival
        # the weights', which is where the int8 KV cache earns its keep.
        out["decode_int8_kv_tokens_per_sec"] = round(max(dec8kv), 1)
        flush_partial()
    longctx = attempts(bench_decode_long_context, "long-context decode bench",
                       n=1)
    if longctx:
        kern_tok, einsum_tok = longctx[0]
        out["decode_longctx_tokens_per_sec"] = round(kern_tok, 1)
        out["decode_longctx_einsum_tokens_per_sec"] = round(einsum_tok, 1)
        out["decode_longctx_kernel_speedup"] = round(
            kern_tok / einsum_tok, 3)
        flush_partial()
    # Per-side MIN over attempts: kernel timings are bimodal through the
    # relay (round-5 measured the same flash program at 5.1 and 8.9 ms
    # across identical calls while XLA held 8.6) — one attempt can land
    # either mode and misreport the capability ratio by ~2x.
    attn = attempts(bench_attention, "attention kernel bench", n=2)
    if attn:
        flash_ms = min(a[0] for a in attn)
        xla_ms = min(a[1] for a in attn)
        out["flash_attn_fwdbwd_ms"] = round(flash_ms, 3)
        out["xla_attn_fwdbwd_ms"] = round(xla_ms, 3)
        out["flash_attn_speedup"] = round(xla_ms / flash_ms, 3)
        flush_partial()
    tsweep = attempts(bench_attention_tsweep, "attention T sweep", n=1)
    if tsweep:
        out["flash_attn_t_sweep"] = tsweep[0]
        flush_partial()
    blocks = attempts(bench_attention_blocks, "attention block sweep", n=1)
    if blocks:
        # Settles the round-2 block_q question (BASELINE.md:95-99) with a
        # recorded per-block number instead of an unconfirmed default bump.
        out["flash_attn_block_sweep_ms"] = blocks[0]
        flush_partial()
    sv = attempts(bench_serving_continuous, "continuous serving bench", n=1)
    if sv:
        rps, ttft_ms, overlap_rps, ms_rps, mso_rps, itl_p50 = sv[0]
        out["serving_requests_per_sec"] = round(rps, 2)
        out["serving_mean_ttft_ms"] = round(ttft_ms, 2)
        out["serving_overlap_requests_per_sec"] = round(overlap_rps, 2)
        out["serving_multistep_requests_per_sec"] = round(ms_rps, 2)
        out["serving_multistep_overlap_requests_per_sec"] = round(
            mso_rps, 2)
        out["serving_decode_p50_intertoken_ms"] = round(itl_p50, 3)
        flush_partial()
    pc = attempts(bench_decode_paged_call, "paged decode call bench", n=1)
    if pc:
        # The paged-decode device floor as first-class keys: per-call
        # kernel latency (t=1 sync step vs t=8 fused multi-row step)
        # and the analytic launches per 16-token block per mode (fused
        # <= 2 asserted in-bench — BASELINE.md's 8-launch floor).
        call_ms, fused_ms, sync_lpb, fused_lpb = pc[0]
        out["decode_paged_call_ms"] = round(call_ms, 3)
        out["decode_paged_fused_call_ms"] = round(fused_ms, 3)
        out["decode_paged_launches_per_block_sync"] = int(sync_lpb)
        out["decode_paged_launches_per_block_fused"] = int(fused_lpb)
        flush_partial()
    pl = attempts(bench_serving_pipeline, "pipelined serving bench", n=1)
    if pl:
        # pipeline_depth=1 vs 0, same workload/process: token-identical
        # asserted in-bench, pipelined inter-token p50 strictly better.
        pipe_itl, base_itl, pipe_rps = pl[0]
        out["serving_pipeline_decode_p50_intertoken_ms"] = round(
            pipe_itl, 3)
        out["serving_pipeline_baseline_p50_intertoken_ms"] = round(
            base_itl, 3)
        out["serving_pipeline_requests_per_sec"] = round(pipe_rps, 2)
        out["serving_pipeline_speedup"] = round(base_itl / pipe_itl, 3)
        flush_partial()
    fp = attempts(bench_serving_fused_prefill,
                  "fused prefill serving bench", n=1)
    if fp:
        # Fused prefill+decode ticks vs the phase-split chunked tick:
        # token-identical asserted in-bench, interactive inter-token
        # p99 under long-prompt interference strictly better fused.
        fused_p99, split_p99, fused_rps = fp[0]
        out["serving_fused_itl_p99_ms"] = round(fused_p99, 3)
        out["serving_fused_split_itl_p99_ms"] = round(split_p99, 3)
        out["serving_fused_speedup"] = round(split_p99 / fused_p99, 3)
        out["serving_fused_requests_per_sec"] = round(fused_rps, 2)
        flush_partial()
    wu = attempts(bench_serving_warmup, "serving warmup probe", n=1)
    if wu:
        # Cold vs AOT-warmed first-request TTFT (warm < cold asserted).
        warm_ttft, cold_ttft, warm_s = wu[0]
        out["serving_warm_first_ttft_ms"] = round(warm_ttft, 2)
        out["serving_cold_first_ttft_ms"] = round(cold_ttft, 2)
        out["serving_warmup_seconds"] = round(warm_s, 2)
        flush_partial()
    psv = attempts(bench_serving_prefix_cache,
                   "prefix-cache serving bench", n=1)
    if psv:
        # Shared-system-prompt workload: warm (prefix cached) vs cold
        # TTFT, with warm completions asserted equal to cold prefill.
        warm_ttft, cold_ttft, rps, hit_rate = psv[0]
        out["serving_prefix_hit_ttft_ms"] = round(warm_ttft, 2)
        out["serving_prefix_cold_ttft_ms"] = round(cold_ttft, 2)
        out["serving_prefix_requests_per_sec"] = round(rps, 2)
        out["serving_prefix_cache_hit_rate"] = round(hit_rate, 3)
        flush_partial()
    sc = attempts(bench_serving_spec_compose,
                  "speculative composition bench", n=1)
    if sc:
        # Spec composed with the fast path (the bypass burn-down):
        # spec+prefix warm TTFT strictly below spec cold (streams
        # equal), spec inter-token p50 vs the non-spec baseline
        # (perfect-draft ceiling), and a live spec fleet drain-migrated
        # mid-stream with ZERO lost requests (asserted in-bench).
        warm_ttft, cold_ttft, spec_itl, base_itl, accept, resumes = sc[0]
        out["serving_spec_warm_ttft_ms"] = round(warm_ttft, 2)
        out["serving_spec_cold_ttft_ms"] = round(cold_ttft, 2)
        out["serving_spec_prefix_speedup"] = round(
            cold_ttft / warm_ttft, 3)
        out["serving_spec_decode_p50_intertoken_ms"] = round(spec_itl, 3)
        out["serving_spec_baseline_p50_intertoken_ms"] = round(
            base_itl, 3)
        out["serving_spec_acceptance_rate"] = round(accept, 3)
        out["serving_spec_migration_lost_requests"] = 0
        out["serving_spec_migration_resumes"] = int(resumes)
        flush_partial()
    lsv = attempts(bench_serving_longctx, "long-context serving bench",
                   n=1)
    if lsv:
        tok_s, ttft_ms = lsv[0]
        out["serving_longctx_tokens_per_sec"] = round(tok_s, 1)
        out["serving_longctx_ttft_ms"] = round(ttft_ms, 2)
        flush_partial()
    msv = attempts(bench_serving_continuous_mesh,
                   "mesh continuous serving bench", n=1)
    if msv and msv[0] is not None:  # >1 visible device: dp x tp serving
        out["serving_mesh_requests_per_sec"] = round(msv[0], 2)
        flush_partial()
    fl = attempts(bench_fleet_serving, "fleet serving bench", n=1)
    if fl:
        # Gateway + 2 local CPU replicas: the online multi-replica path
        # (fleet subsystem) — tracks fleet overhead, not chip speed.
        rps, ttft_ms, queue_wait_p50, queue_wait_p99 = fl[0]
        out["fleet_requests_per_sec"] = round(rps, 2)
        out["fleet_mean_ttft_ms"] = round(ttft_ms, 2)
        out["fleet_queue_wait_p50_ms"] = round(queue_wait_p50, 2)
        out["fleet_queue_wait_p99_ms"] = round(queue_wait_p99, 2)
        flush_partial()
    asb = attempts(bench_fleet_autoscale, "fleet autoscale bench", n=1)
    if asb:
        # Control-plane reaction: surge start -> new replica routable,
        # and a blue-green rollout under continuous traffic with ZERO
        # failed requests asserted in-bench (downtime 0 by contract).
        reaction_s, downtime_ms = asb[0]
        out["fleet_scaleup_reaction_s"] = round(reaction_s, 2)
        out["fleet_rollout_downtime_ms"] = round(downtime_ms, 2)
        flush_partial()
    pr = attempts(bench_fleet_priority, "fleet priority bench", n=1)
    if pr:
        # SLO isolation: interactive p99 held near its unloaded value
        # under a background flood (WFQ + preemption, asserted
        # in-bench), and ZERO lost requests across a migrating
        # scale-down + rollout (drain-migrate-kill).
        unloaded_p99, pri_p99, bg_p99, lost = pr[0]
        out["fleet_priority_p99_ttft_ms"] = round(pri_p99, 2)
        out["fleet_priority_unloaded_p99_ttft_ms"] = round(
            unloaded_p99, 2)
        out["fleet_background_p99_ttft_ms"] = round(bg_p99, 2)
        out["fleet_migration_lost_requests"] = int(lost)
        flush_partial()
    sk = attempts(bench_fleet_soak, "fleet chaos soak", n=1)
    if sk:
        # Failure containment under seeded chaos: zero lost requests
        # and bounded retry amplification through a gray-slow replica
        # (breaker-isolated while heartbeat-alive), a SIGKILL +
        # autoscaler self-heal, a link sever, and a rollout — with the
        # breaker-disabled control arm's p99 degradation recorded next
        # to the protected p99 (in-bench asserted strictly worse).
        (lost, amplification, on_p99, control_p99, n_soak,
         slow_attempt_ms, traces_detailed) = sk[0]
        out["fleet_soak_lost_requests"] = int(lost)
        out["fleet_soak_retry_amplification"] = round(amplification, 3)
        out["fleet_soak_p99_ms"] = round(on_p99, 2)
        out["fleet_soak_nobreaker_p99_ms"] = round(control_p99, 2)
        out["fleet_soak_requests"] = int(n_soak)
        # Tracing attribution (PR 10): the injected gray-failure delay
        # as seen INSIDE a retained trace's router span toward the
        # slow replica, plus how many traces kept full detail under
        # tail-based retention.
        out["fleet_trace_slow_attempt_ms"] = round(slow_attempt_ms, 2)
        out["fleet_trace_detailed_retained"] = int(traces_detailed)
        flush_partial()
    sm = attempts(bench_fleet_sim, "fleet simulator bench", n=1)
    if sm:
        # Virtual-clock fleet simulator: the real control plane driven
        # at 1000-replica / 1M-request scale in seconds of CPU, plus
        # the soak-replay fidelity gate (gray-failure isolation, zero
        # lost, bounded amplification — asserted in-bench).
        (events_ps, replica_s_ps, wall_s, n_sim, sim_s, fid_amp,
         eps_10k) = sm[0]
        out["sim_events_per_sec"] = round(events_ps, 1)
        out["sim_replicas_per_wallclock_sec"] = round(replica_s_ps, 1)
        out["fleet_sim_wall_s"] = round(wall_s, 2)
        out["fleet_sim_requests"] = int(n_sim)
        out["fleet_sim_virtual_seconds"] = round(sim_s, 1)
        out["fleet_sim_soak_amplification"] = round(fid_amp, 3)
        # 10k-replica diurnal replay (sharded heartbeats, day/night
        # envelope): the hot-path floor held at 10x replica count.
        out["sim_events_per_sec_10k"] = round(eps_10k, 1)
        flush_partial()
    ol = attempts(bench_fleet_offline_lane, "offline lane bench", n=1)
    if ol:
        # The offline lane: utilization strictly higher with the batch
        # lane on, interactive p99 held, zero lost, backlog complete —
        # all asserted in-bench.
        on_util, off_util, on_p99, off_p99, deferrals, n_batch = ol[0]
        out["fleet_offline_utilization"] = round(on_util, 4)
        out["fleet_offline_baseline_utilization"] = round(off_util, 4)
        out["fleet_offline_interactive_p99_ms"] = round(on_p99, 2)
        out["fleet_offline_baseline_interactive_p99_ms"] = round(
            off_p99, 2)
        out["fleet_offline_batch_completed"] = int(n_batch)
        out["fleet_offline_batch_deferrals"] = int(deferrals)
        out["fleet_offline_lost_requests"] = 0
        flush_partial()
    ka = attempts(bench_http_keepalive, "http keep-alive bench", n=1)
    if ka:
        # Before/after connection reuse on the HTTP ingress: one
        # kept-alive connection vs a fresh connect per request.
        keep_rps, close_rps = ka[0]
        out["http_keepalive_requests_per_sec"] = round(keep_rps, 1)
        out["http_per_conn_requests_per_sec"] = round(close_rps, 1)
        out["http_keepalive_speedup"] = round(keep_rps / close_rps, 3)
        flush_partial()
    gc = attempts(bench_fleet_gateway_concurrency,
                  "gateway concurrency bench", n=1)
    if gc:
        # Front-door scale (ROADMAP item 2): >= 1000 concurrent client
        # connections on ONE event-loop gateway with bounded p99
        # first-token latency, and a two-gateway kill soak where p99
        # TTFT holds and zero idempotent requests are lost across the
        # client failover — all asserted in-bench.
        conns, flood_p99, pre_p99, post_p99, gw_lost = gc[0]
        out["fleet_gateway_concurrent_connections"] = int(conns)
        out["fleet_gateway_flood_p99_ttft_ms"] = round(flood_p99, 2)
        out["fleet_gateway_prekill_p99_ttft_ms"] = round(pre_p99, 2)
        out["fleet_gateway_kill_p99_ttft_ms"] = round(post_p99, 2)
        out["fleet_gateway_lost_requests"] = int(gw_lost)
        flush_partial()
    gp = attempts(bench_fleet_gateway_procs,
                  "multi-process gateway bench", n=1)
    if gp:
        # Multi-process front door: N real gateway OS processes behind
        # one SO_REUSEPORT door (or per-process discovery ports) must
        # strictly out-serve one process at saturation, and a mid-run
        # SIGKILL of one process loses zero idempotent requests
        # (failover replay across a process death) — asserted in-bench.
        (rps1, rpsn, p99_1, pre99, post99, pl, mode) = gp[0]
        out["fleet_gateway_procs_rps_1"] = round(rps1, 1)
        out["fleet_gateway_procs_rps_n"] = round(rpsn, 1)
        out["fleet_gateway_procs_p99_ttft_ms"] = round(p99_1, 2)
        out["fleet_gateway_procs_prekill_p99_ttft_ms"] = round(pre99, 2)
        out["fleet_gateway_procs_kill_p99_ttft_ms"] = round(post99, 2)
        out["fleet_gateway_procs_lost_requests"] = int(pl)
        out["fleet_gateway_procs_mode"] = mode
        flush_partial()
    tro = attempts(bench_fleet_trace_overhead, "trace overhead bench",
                   n=1)
    if tro:
        # Tracing overhead bound: full-detail-on-every-request p99 vs
        # summary-only p99 on the same seeded stub workload (asserted
        # within 5% + 1ms in-bench).
        overhead_pct, p99_sum, p99_det = tro[0]
        out["fleet_trace_overhead_pct"] = round(overhead_pct, 2)
        out["fleet_trace_summary_p99_ms"] = round(p99_sum, 3)
        out["fleet_trace_detail_p99_ms"] = round(p99_det, 3)
        flush_partial()
    dg = attempts(bench_fleet_disagg, "disaggregated fleet bench", n=1)
    if dg:
        # Mixed long-prompt/long-decode workload: dedicated prefill +
        # decode tiers (KV pages exported over raw wire frames) vs a
        # same-size unified fleet; decode inter-token p50 is asserted
        # strictly better disaggregated (no prefill-induced stalls).
        dis_ttft, dis_itl, uni_ttft, uni_itl, kv_mb_s = dg[0]
        out["serving_disagg_ttft_ms"] = round(dis_ttft, 2)
        out["serving_disagg_decode_p50_intertoken_ms"] = round(dis_itl, 3)
        out["serving_unified_mixed_ttft_ms"] = round(uni_ttft, 2)
        out["serving_unified_mixed_decode_p50_intertoken_ms"] = round(
            uni_itl, 3)
        out["fleet_kv_transfer_mb_per_sec"] = round(kv_mb_s, 2)
        flush_partial()
    fa = attempts(bench_fleet_prefix_affinity,
                  "fleet prefix-affinity bench", n=1)
    if fa:
        # Shared prefixes steered to the replica already caching them.
        hit_rate, rps = fa[0]
        out["fleet_prefix_affinity_hit_rate"] = round(hit_rate, 3)
        out["fleet_prefix_requests_per_sec"] = round(rps, 2)
        flush_partial()
    ks = attempts(bench_fleet_sessions, "fleet KV-tier sessions bench",
                  n=1)
    if ks:
        # Multi-turn session resume-from-tier vs cold full-history
        # prefill (streams asserted token-identical in-bench), plus
        # the shared prefix as a FLEET resource (prefilled once,
        # router-directed).
        resumed, cold, hit_rate, prefills, aff = ks[0]
        out["fleet_session_resume_ttft_ms"] = round(resumed, 2)
        out["fleet_session_cold_ttft_ms"] = round(cold, 2)
        out["fleet_session_speedup"] = round(cold / max(1e-9, resumed), 3)
        out["fleet_kv_tier_hit_rate"] = round(hit_rate, 3)
        out["fleet_shared_prefix_prefills"] = prefills
        out["fleet_shared_prefix_affinity_hit_rate"] = round(aff, 3)
        flush_partial()
    fb = attempts(bench_fleet_fabric, "fleet KV fabric bench", n=1)
    if fb:
        # Cross-host KV fabric: direct replica-to-replica artifact
        # streaming vs the router-relay fallback on the same workload
        # (strictly faster asserted in-bench), and a kv_replication=2
        # fleet riding out a parker SIGKILL with zero lost sessions.
        # The direct rate is the headline transfer number — it
        # supersedes the disagg-derived sample above with a dedicated
        # same-workload measurement.
        direct_mb_s, relay_mb_s, resumed, fetch_hits = fb[0]
        out["fleet_kv_transfer_mb_per_sec"] = round(direct_mb_s, 2)
        out["fleet_kv_relay_mb_per_sec"] = round(relay_mb_s, 2)
        out["fleet_fabric_resumed_sessions"] = int(resumed)
        out["fleet_fabric_lost_sessions"] = 0
        out["fleet_fabric_forwarded_fetch_hits"] = int(fetch_hits)
        flush_partial()
    mm = attempts(bench_fleet_multimodel, "fleet multi-model bench",
                  n=1)
    if mm:
        # Model catalog: cross-model trading under a fixed budget,
        # warm-pool cold start vs cold relaunch, adapter hot-swap
        # under traffic, per-tenant x model metering — all asserted
        # in-bench.
        out.update(mm[0])
        flush_partial()
    gg = attempts(bench_fleet_gang, "fleet gang replica bench", n=1)
    if gg:
        # One model sharded across a gang of member tasks, served as
        # ONE replica: streams asserted token-identical to a
        # single-process fleet, zero lost requests across a mid-decode
        # gang-member SIGKILL and across a gang drain-migration.
        gang_itl, single_itl, reform_s = gg[0]
        out["fleet_gang_itl_p50_ms"] = round(gang_itl, 3)
        out["fleet_single_itl_p50_ms"] = round(single_itl, 3)
        out["fleet_gang_reform_s"] = round(reform_s, 2)
        flush_partial()
    rw = attempts(bench_ring_window, "ring window bench", n=1)
    if rw and rw[0] is not None:    # >1 visible device: sp ring
        flash_ms, xla_ms = rw[0]
        out["ring_window_flash_ms"] = round(flash_ms, 3)
        out["ring_window_einsum_ms"] = round(xla_ms, 3)
        out["ring_window_flash_speedup"] = round(xla_ms / flash_ms, 3)
        flush_partial()
    pb = attempts(pipeline_bubble_stats, "pipeline schedule stats", n=1)
    if pb:
        out.update(pb[0])
        flush_partial()
    bw = attempts(bench_bandwidth, "bandwidth bench", n=1)
    if bw:
        out.update(bw[0])
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
