"""Project benchmark: mnist_replica steps/sec/chip (BASELINE.json metric).

Runs the reference's canonical workload — the mnist_replica trainer at its
published scale (batch 100, hidden 100, mnist_replica.py:70-73) — as a jit'd
sync-SGD step on this host's accelerator, plus the flagship transformer as a
secondary throughput probe, and prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
baseline is our own first measured value on the v5e-1 chip, recorded in
BASELINE_SELF below; >1.0 means faster than round-1's framework.
"""

import json
import time

import numpy as np

# Round-1 self-measured baseline on one v5e chip (steps/sec/chip for the
# mnist_replica workload below), measured with the chained-steps +
# final-host-fetch methodology.  Established 2026-07-28; see BASELINE.md.
BASELINE_SELF = 1400.0


def bench_mnist_replica(steps=2000, warmup=100):
    # Protocol (round-1 final, see BASELINE.md): K=20 optimizer steps fused
    # per dispatch via lax.scan; `steps` counts individual optimizer steps;
    # the timed chain ends in a real host fetch.  main() runs this
    # best-of-3 to shed remote-attach latency jitter.
    import jax
    import optax
    from tfmesos_tpu.models import mlp
    from tfmesos_tpu.parallel.mesh import build_mesh
    from tfmesos_tpu.parallel.sharding import make_global_batch
    from tfmesos_tpu.train import data as datalib
    from tfmesos_tpu.train.trainer import make_train_step

    n_chips = max(1, jax.device_count())
    mesh = build_mesh()  # every chip on a data-parallel axis
    cfg = mlp.MLPConfig(hidden=100)
    params = mlp.init_params(cfg, jax.random.PRNGKey(0))
    opt = optax.sgd(0.01)  # reference lr (mnist_replica.py:71)
    # K steps per dispatch: one host round-trip amortizes over a scanned
    # block of optimizer steps — the TPU-first answer to dispatch latency.
    k = 20
    step = make_train_step(lambda p, b: mlp.loss_fn(cfg, p, b), opt, mesh=mesh,
                           steps_per_call=k)
    params, opt_state = step.place(params, opt.init(params))

    ds = datalib.SyntheticMNIST()
    # Reference batch 100, rounded so it shards evenly over the chips —
    # the step really runs on all of them, so dividing by n_chips is honest.
    local_bs = max(1, 100 // n_chips)
    gen = ds.batches(local_bs * n_chips)

    def stacked_batch():
        ms = [next(gen) for _ in range(k)]
        return make_global_batch(
            mesh, {key: np.stack([m[key] for m in ms]) for key in ms[0]},
            batch_dim=1)

    batch = stacked_batch()
    for _ in range(max(1, warmup // k)):
        params, opt_state, metrics = step(params, opt_state, batch)
    float(metrics["loss"])  # drain the warmup chain with a real host fetch
    calls = max(1, steps // k)
    t0 = time.perf_counter()
    for _ in range(calls):
        params, opt_state, metrics = step(params, opt_state, batch)
    # Steps chain through donated params, so the device must run them in
    # order; the host fetch forces completion of the whole chain (on some
    # remote-attached runtimes block_until_ready acks early).
    final_loss = float(np.asarray(metrics["loss"]))
    dt = time.perf_counter() - t0
    return calls * k / dt / n_chips, final_loss


def bench_transformer_tokens(iters=20):
    import jax
    import jax.numpy as jnp
    from tfmesos_tpu.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=8192, d_model=512, n_layers=8, n_heads=8, d_ff=1408,
        max_seq_len=1024, dtype=jnp.bfloat16)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    b, t = 8, 1024
    tokens = jax.random.randint(jax.random.PRNGKey(1), (b, t + 1), 0,
                                cfg.vocab_size, dtype=jnp.int32)

    import optax

    # Chain params through a real optimizer update each iteration so no
    # remote runtime can overlap/dedup the iterations, and finish with a
    # host fetch (see bench_mnist_replica).
    opt = optax.sgd(1e-4)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(
            lambda p: transformer.loss_fn(cfg, p, {"tokens": tokens})[0])(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    params, opt_state, loss = step(params, opt_state)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step(params, opt_state)
    float(np.asarray(loss))
    dt = (time.perf_counter() - t0) / iters
    return b * t / dt  # tokens/sec (fwd+bwd+update)


def main():
    import jax

    import sys
    import traceback

    # Best-of-3: the remote-attach relay adds ±40% latency jitter between
    # runs; the max is the least-interference estimate of chip capability.
    # Individual runs may die on relay hiccups — keep whatever succeeded,
    # with full tracebacks on stderr so deterministic bugs stay debuggable.
    def attempts(fn, label, n=3):
        results = []
        for _ in range(n):
            try:
                results.append(fn())
            except Exception:
                print(f"{label} run failed:", file=sys.stderr)
                traceback.print_exc(file=sys.stderr)
        return results

    runs = attempts(lambda: bench_mnist_replica(steps=800), "bench")
    if not runs:
        raise SystemExit("all benchmark runs failed")
    value, final_loss = max(runs)
    tokens_runs = attempts(lambda: bench_transformer_tokens(iters=10),
                           "transformer bench")
    tokens_per_sec = max(tokens_runs) if tokens_runs else None
    out = {
        "metric": "mnist_replica_steps_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "steps/s/chip",
        "vs_baseline": round(value / BASELINE_SELF, 3),
        "backend": jax.default_backend(),
        "n_chips": jax.device_count(),
        "final_loss": round(final_loss, 4),
    }
    if tokens_per_sec is not None:
        out["transformer_tokens_per_sec"] = round(tokens_per_sec, 1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
