#!/bin/bash
# Provision a TPU-VM as a Mesos agent advertising its chips as the custom
# scalar resource `tpus` (reference analogue: misc/setup-aws-g2.sh, which
# installed CUDA + nvidia-docker on GPU agents — none of that exists here).
set -euo pipefail

MESOS_MASTER=${1:?usage: setup-tpu-vm.sh <mesos-master:port> [num-chips]}
NUM_CHIPS=${2:-4}

# 1. Mesos agent (distro package or your org's build).
apt-get update && apt-get install -y mesos

# 2. Advertise TPU chips as a custom resource; cpus/mem are auto-detected.
mkdir -p /etc/mesos-agent
echo "tpus:${NUM_CHIPS}" > /etc/mesos-agent/resources
echo "docker,mesos" > /etc/mesos-agent/containerizers
echo "${MESOS_MASTER}" > /etc/mesos-agent/master

# 3. The MESOS containerizer needs the TPU device nodes plumbed into task
#    containers; /dev/vfio and /dev/accel* must be world-accessible on the
#    host (TPU-VM images ship them so by default).
ls /dev/accel* >/dev/null

systemctl restart mesos-agent
echo "agent up: $(hostname) advertising tpus:${NUM_CHIPS} to ${MESOS_MASTER}"
